"""dintmon: device counter plane + trace layer (OBSERVABILITY.md).

The contract under test, per acceptance criteria:
  * counter totals RECONCILE with the stats vector the host already
    fetches (committed/aborted by cause), drains included, on both dense
    engines, both generic pipelines, and both sharded paths;
  * counters are reproducible (same seed -> same values), bit-identical
    between the XLA and Pallas random-access backends and between the
    generic and dense engines on the parity workloads (PARITY_NAMES);
  * per-device counters sum across shards to the psummed stats totals;
  * monitoring OFF (the default) changes no engine output;
  * the JSONL trace schema is stable and the dintmon CLI works end to end.

Builders are cached at module scope (one compile per configuration) so
the whole file stays cheap inside the tier-1 budget; every test drives a
FRESH population through the shared compiled runner.
"""
import functools
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from dint_tpu import monitor as M
from dint_tpu.monitor import counters as mc

pytestmark = pytest.mark.monitor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = jax.random.PRNGKey

# one shared tiny geometry -> one compile per (engine, monitor, backend)
N_SUB = 300
N_ACC = 400
W = 64
VW = 4
CPB = 2


# --------------------------------------------------------------- registry


def test_registry_is_schema_stable():
    assert len(mc.ALL_NAMES) == mc.N_COUNTERS
    assert len(set(mc.ALL_NAMES)) == mc.N_COUNTERS          # unique names
    assert [mc.COUNTER_INDEX[n] for n in mc.ALL_NAMES] == \
        list(range(mc.N_COUNTERS))                          # contiguous
    for n in mc.ALL_NAMES:
        assert mc.COUNTER_KINDS[n] in (mc.FLOW, mc.GAUGE)
        assert mc.COUNTER_DOCS[n]
    assert set(mc.PARITY_NAMES) <= set(mc.ALL_NAMES)
    assert "ring_hwm" in mc.GAUGE_NAMES


def test_delta_wraps_u32():
    prev = dict(mc.zeros_dict(), txn_attempted=0xFFFF_FFF0)
    cur = dict(mc.zeros_dict(), txn_attempted=0x10)
    d = mc.delta(cur, prev)
    assert d["txn_attempted"] == 0x20       # wrapped, still exact
    assert mc.delta(cur, None)["txn_attempted"] == 0x10


# ------------------------------------------------------- cached builders


@functools.lru_cache(maxsize=None)
def _td_build(monitor, use_pallas=False, use_fused=False):
    from dint_tpu.engines import tatp_dense as td

    return td.build_pipelined_runner(
        N_SUB, w=W, val_words=VW, cohorts_per_block=CPB,
        use_pallas=use_pallas, use_fused=use_fused, monitor=monitor)


@functools.lru_cache(maxsize=None)
def _sb_build(monitor, use_pallas=False, use_hotset=False,
              use_fused=False):
    from dint_tpu.engines import smallbank_dense as sd

    return sd.build_pipelined_runner(
        N_ACC, w=W, cohorts_per_block=CPB, use_pallas=use_pallas,
        use_hotset=use_hotset, use_fused=use_fused, monitor=monitor)


@functools.lru_cache(maxsize=None)
def _tp_build(monitor):
    from dint_tpu.engines import tatp_pipeline as tp

    return tp.build_pipelined_runner(
        N_SUB, w=W, val_words=VW, cohorts_per_block=CPB, monitor=monitor)


# ---------------------------------------------------------- dense engines


def _run_tatp_dense(monitor, blocks=3, seed=0, use_pallas=False,
                    use_fused=False):
    from dint_tpu.engines import tatp_dense as td

    db = td.populate(np.random.default_rng(seed), N_SUB, val_words=VW)
    run, init, drain = _td_build(monitor, use_pallas, use_fused)
    carry = init(db)
    tot = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, s = run(carry, jax.random.fold_in(KEY(seed), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    out = drain(carry)
    tot += np.asarray(out[1], np.int64).sum(axis=0)
    snap = M.snapshot(out[2]) if monitor else None
    return out[0], tot, snap


def test_tatp_dense_reconciles_with_stats():
    from dint_tpu.engines import tatp_dense as td

    _, tot, snap = _run_tatp_dense(True)
    assert snap["txn_attempted"] == tot[td.STAT_ATTEMPTED]
    assert snap["txn_committed"] == tot[td.STAT_COMMITTED]
    assert snap["ab_lock"] == tot[td.STAT_AB_LOCK]
    assert snap["ab_missing"] == tot[td.STAT_AB_MISSING]
    assert snap["ab_validate"] == tot[td.STAT_AB_VALIDATE]
    assert snap["magic_bad"] == tot[td.STAT_MAGIC_BAD] == 0
    # internal ledgers close
    assert snap["lock_requests"] == \
        snap["lock_granted"] + snap["lock_rejected"]
    assert snap["lock_rejected"] == \
        snap["lock_reject_held"] + snap["lock_reject_arb"]
    assert snap["dispatch_xla"] == snap["steps"]
    assert snap["dispatch_pallas"] == 0
    assert snap["log_appends"] == snap["install_writes"] > 0
    assert snap["ring_hwm"] > 0
    assert snap["repl_push_hop1"] == 0      # single chip: no ICI pushes


def test_tatp_dense_monitoring_off_is_bit_identical():
    db_off, tot_off, _ = _run_tatp_dense(False)
    db_on, tot_on, _ = _run_tatp_dense(True)
    assert tot_off.tolist() == tot_on.tolist()
    assert np.array_equal(np.asarray(db_off.meta), np.asarray(db_on.meta))
    assert np.array_equal(np.asarray(db_off.val), np.asarray(db_on.val))
    assert np.array_equal(np.asarray(db_off.log.entries),
                          np.asarray(db_on.log.entries))


def test_tatp_dense_counters_reproducible_across_runs():
    _, _, a = _run_tatp_dense(True, seed=3)
    _, _, b = _run_tatp_dense(True, seed=3)
    assert a == b
    _, _, c = _run_tatp_dense(True, seed=4)
    assert a != c           # and they are not trivially constant


def test_tatp_dense_counters_bit_identical_xla_vs_pallas():
    # CPU runs the kernels in interpret mode (ops/pallas_gather); the
    # counter plane must not observe the backend apart from the dispatch
    # accounting counters themselves
    _, tot_x, a = _run_tatp_dense(True, use_pallas=False)
    _, tot_p, b = _run_tatp_dense(True, use_pallas=True)
    assert tot_x.tolist() == tot_p.tolist()
    assert a["dispatch_xla"] == b["dispatch_pallas"] == a["steps"]
    assert a["dispatch_pallas"] == b["dispatch_xla"] == 0
    drop = ("dispatch_xla", "dispatch_pallas")
    assert {k: v for k, v in a.items() if k not in drop} == \
        {k: v for k, v in b.items() if k not in drop}


def _run_sb_dense(monitor, blocks=3, seed=1, use_pallas=False,
                  use_hotset=False, use_fused=False):
    from dint_tpu.engines import smallbank_dense as sd

    db = sd.create(N_ACC)
    run, init, drain = _sb_build(monitor, use_pallas, use_hotset,
                                 use_fused)
    carry = init(db)
    tot = np.zeros(sd.N_STATS, np.int64)
    for i in range(blocks):
        carry, s = run(carry, jax.random.fold_in(KEY(seed), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    out = drain(carry)
    tot += np.asarray(out[1], np.int64).sum(axis=0)
    snap = M.snapshot(out[2]) if monitor else None
    return out[0], tot, snap


def test_sb_dense_reconciles_and_off_identical():
    from dint_tpu.engines import smallbank_dense as sd

    db_on, tot, snap = _run_sb_dense(True)
    assert snap["txn_attempted"] == tot[sd.STAT_ATTEMPTED]
    assert snap["txn_committed"] == tot[sd.STAT_COMMITTED]
    assert snap["ab_lock"] == tot[sd.STAT_AB_LOCK]
    assert snap["ab_logic"] == tot[sd.STAT_AB_LOGIC]
    assert snap["lock_requests"] == \
        snap["lock_granted"] + snap["lock_rejected"]
    assert snap["lock_rejected"] == \
        snap["lock_reject_held"] + snap["lock_reject_arb"]
    assert snap["install_writes"] > 0 and snap["ring_hwm"] > 0

    db_off, tot_off, _ = _run_sb_dense(False)
    assert tot_off.tolist() == tot.tolist()
    assert np.array_equal(np.asarray(db_off.bal), np.asarray(db_on.bal))


def test_sb_dense_counters_bit_identical_xla_vs_pallas():
    _, _, a = _run_sb_dense(True, use_pallas=False)
    _, _, b = _run_sb_dense(True, use_pallas=True)
    drop = ("dispatch_xla", "dispatch_pallas")
    assert {k: v for k, v in a.items() if k not in drop} == \
        {k: v for k, v in b.items() if k not in drop}


def test_sb_dense_hot_counters_reconcile():
    """dintcache counters (round 10): hot_hits + hot_cold_rows accounts
    every partitioned gather lane (3 gathers x w*L lanes per step at this
    exact-lock geometry), refresh bytes bill the VMEM mirror copies on
    the pallas route only, and every pre-round-10 counter is untouched
    by the hot tier (it changes WHERE bytes come from, not outcomes)."""
    from dint_tpu.engines import smallbank_dense as sd

    blocks = 3
    steps = blocks * CPB + 1                 # + the drain step
    lanes = W * sd.L
    _, tot, base = _run_sb_dense(True)
    db, tot_h, x = _run_sb_dense(True, use_hotset=True)
    _, tot_p, p = _run_sb_dense(True, use_pallas=True, use_hotset=True)
    assert tot.tolist() == tot_h.tolist() == tot_p.tolist()

    hn = db.hot_n
    assert hn == max(1, int(N_ACC * 0.04))
    for snap in (x, p):
        assert snap["hot_hits"] + snap["hot_cold_rows"] == 3 * steps * lanes
        assert snap["hot_hits"] > 0          # the skew really lands hot
    assert x["hot_refresh_bytes"] == 0       # XLA partition: no residency
    assert p["hot_refresh_bytes"] == steps * 3 * 2 * hn * 4
    # the hot split itself is backend-independent
    assert x["hot_hits"] == p["hot_hits"]
    drop = ("dispatch_xla", "dispatch_pallas", "hot_hits",
            "hot_cold_rows", "hot_refresh_bytes")
    assert {k: v for k, v in base.items() if k not in drop} == \
        {k: v for k, v in x.items() if k not in drop} == \
        {k: v for k, v in p.items() if k not in drop}
    assert base["hot_hits"] == base["hot_cold_rows"] == 0


@pytest.mark.slow  # ~18s; fused parity itself is pinned in test_fused_ops
def test_fused_dispatch_counter_reconciles():
    """Round-12 accounting: fused_dispatch counts every step whose paired
    waves ran the megakernels — equal to steps on the fused route, zero
    elsewhere — and it is counted ALONGSIDE the dispatch_xla/pallas
    split, which must stay total (the magic gather still dispatches by
    use_pallas). Every other counter is untouched by fusion: the
    megakernels change dispatch boundaries, not outcomes."""
    from dint_tpu.engines import smallbank_dense as sd  # noqa: F401

    blocks = 2                       # interpret-mode steps: tier-1 budget
    steps_t = blocks * CPB + 2       # 3-stage pipeline: 2 drain steps
    steps_s = blocks * CPB + 1       # 2-stage pipeline: 1 drain step
    _, tot_t, base_t = _run_tatp_dense(True, blocks=blocks)
    _, tot_tf, fus_t = _run_tatp_dense(True, blocks=blocks,
                                       use_fused=True)
    assert tot_t.tolist() == tot_tf.tolist()
    assert base_t["fused_dispatch"] == 0
    assert fus_t["fused_dispatch"] == fus_t["steps"] == steps_t
    assert fus_t["dispatch_xla"] == steps_t  # the split stays total
    assert fus_t["dispatch_pallas"] == 0
    drop = ("fused_dispatch",)
    assert {k: v for k, v in base_t.items() if k not in drop} == \
        {k: v for k, v in fus_t.items() if k not in drop}

    _, tot_s, base_s = _run_sb_dense(True, blocks=blocks)
    _, tot_sf, fus_s = _run_sb_dense(True, blocks=blocks,
                                     use_fused=True)
    assert tot_s.tolist() == tot_sf.tolist()
    assert base_s["fused_dispatch"] == 0
    assert fus_s["fused_dispatch"] == fus_s["steps"] == steps_s
    assert {k: v for k, v in base_s.items() if k not in drop} == \
        {k: v for k, v in fus_s.items() if k not in drop}

    # fused x hotset: the dintcache accounting knows the fused gathers
    # read the main arrays directly (hot_hits stays 0; only the magic /
    # unfused lanes would count) while outcomes stay bit-identical
    _, tot_sh, hot_s = _run_sb_dense(True, blocks=blocks,
                                     use_hotset=True, use_fused=True)
    assert tot_s.tolist() == tot_sh.tolist()
    assert hot_s["fused_dispatch"] == steps_s
    assert hot_s["hot_hits"] == hot_s["hot_cold_rows"] == 0


# -------------------------------------------------------- serve counters


def test_serve_counters_reconcile_and_mirror():
    """Round-17 lane ledger: on the serve-mode builders the occupancy
    counters account every lane of every SERVING step — occupancy +
    padded == width x steps (drain steps inject nothing, so they tally
    nothing) — the shed counter mirrors the host-side admission ledger
    exactly (the trace_dropped two-sided audit pattern), attempted
    follows OCCUPANCY rather than width, and the closed-loop builders
    leave all three at zero."""
    from dint_tpu.engines import tatp_dense as td
    from dint_tpu.serve import cached_runner

    run, init, drain = cached_runner(
        "tatp_dense", N_SUB, val_words=VW, w=W, cohorts_per_block=CPB,
        monitor=True, trace=False, serve=True)
    db = td.populate(np.random.default_rng(0), N_SUB, val_words=VW)
    carry = init(db)
    occs = [np.array([W, W // 2], np.int32), np.array([0, 7], np.int32),
            np.array([W, 0], np.int32)]
    sheds = [np.array([3, 0], np.int32), np.array([0, 0], np.int32),
             np.array([5, 0], np.int32)]
    tot = np.zeros(td.N_STATS, np.int64)
    for i, (o, s) in enumerate(zip(occs, sheds)):
        carry, st = run(carry, jax.random.fold_in(KEY(0), i), o, s)
        tot += np.asarray(st, np.int64).sum(axis=0)
    out = drain(carry)
    tot += np.asarray(out[1], np.int64).sum(axis=0)
    snap = M.snapshot(out[-1])

    n_occ = sum(int(o.sum()) for o in occs)
    steps = len(occs) * CPB                     # serving steps only
    assert snap["serve_occupancy_lanes"] == n_occ
    assert snap["serve_padded_lanes"] == steps * W - n_occ
    assert snap["serve_occupancy_lanes"] + snap["serve_padded_lanes"] \
        == steps * W                            # the reconciliation identity
    assert snap["serve_shed_lanes"] == sum(int(s.sum()) for s in sheds) == 8
    # attempted follows occupancy, not width: masked lanes are no-ops
    assert snap["txn_attempted"] == tot[td.STAT_ATTEMPTED] == n_occ
    assert 0 < snap["txn_committed"] == tot[td.STAT_COMMITTED] <= n_occ

    # the closed loop never touches the serve plane
    _, _, base = _run_tatp_dense(True)
    assert base["serve_occupancy_lanes"] == base["serve_padded_lanes"] \
        == base["serve_shed_lanes"] == 0


def test_mesh_serve_counters_reconcile_and_prefetch_ledger():
    """Round-18 mesh lane ledger: on the 2-D serve-mode runner the
    occupancy identity holds ACROSS the mesh (occ + padded == width x
    serving-steps x devices), the per-host shed mirror reconciles
    host<->device, and the overlap route accounts every prefetched lane:
    route_prefetch_lanes == lock_requests when the double buffer is on,
    0 when it is off — with the per-axis route split identity intact in
    both modes."""
    from dint_tpu.parallel import multihost_sb as mh

    # geometry matches tests/test_dintmesh.py's engines exactly so the
    # process-wide builder memo shares both compiled runners (tier-1
    # wall-clock: this test pays runs, not compiles)
    H, C, BLK, Wm, Nm = 4, 2, 2, 16, 256
    mesh = mh.make_mesh_2d(H, C)
    rng = np.random.default_rng(3)
    occs = [rng.integers(0, Wm + 1, size=(H, C, BLK)).astype(np.int32)
            for _ in range(BLK)]
    sheds = [rng.integers(0, 4, size=(H, C, BLK)).astype(np.int32)
             for _ in range(BLK)]

    snaps = {}
    for overlap in (False, True):
        run, init, drain = mh.build_multihost_sb_runner(
            mesh, Nm, w=Wm, cohorts_per_block=BLK, monitor=True,
            serve=True, overlap=overlap)
        carry = init(mh.create_multihost_sb(mesh, Nm))
        for i, (o, sh) in enumerate(zip(occs, sheds)):
            carry, _ = run(carry, jax.random.fold_in(KEY(5), i), o, sh)
        _, _, cnt = drain(carry)
        snaps[overlap] = M.snapshot(cnt)

    n_occ = sum(int(o.sum()) for o in occs)
    steps = len(occs) * BLK                      # serving steps only
    for overlap, snap in snaps.items():
        assert snap["serve_occupancy_lanes"] == n_occ, overlap
        assert snap["serve_occupancy_lanes"] + snap["serve_padded_lanes"] \
            == steps * Wm * H * C, overlap       # mesh-wide identity
        # host<->device shed mirror: the device ledger equals the sum of
        # the per-host tallies the host pushed through the occ/shed slots
        assert snap["serve_shed_lanes"] == sum(int(s.sum()) for s in sheds)
        assert snap["txn_attempted"] == n_occ, overlap
        # per-axis route split survives the double buffer
        assert snap["route_ici_lanes"] + snap["route_dcn_lanes"] == \
            snap["lock_requests"] + snap["install_writes"], overlap

    # the prefetch ledger: every valid lock-request lane was exchanged
    # one step early under overlap; the unoverlapped route never touches
    # the counter
    assert snaps[False]["route_prefetch_lanes"] == 0
    assert snaps[True]["route_prefetch_lanes"] == \
        snaps[True]["lock_requests"] > 0
    # scheduling must not change WHAT was locked/committed
    for k in ("lock_requests", "txn_committed", "install_writes"):
        assert snaps[False][k] == snaps[True][k], k


# ------------------------------------------------------- generic engines


def test_generic_smallbank_reconciles():
    from dint_tpu.engines import smallbank_pipeline as sp

    st = sp.create_stacked(N_ACC)
    run = sp.build_runner(N_ACC, w=W, cohorts_per_block=CPB, monitor=True)
    carry = (st, M.create())
    tot = np.zeros(sp.N_STATS, np.int64)
    for i in range(2):
        carry, s = run(carry, jax.random.fold_in(KEY(1), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    snap = M.snapshot(carry[1])
    assert snap["txn_attempted"] == tot[sp.STAT_ATTEMPTED]
    assert snap["txn_committed"] == tot[sp.STAT_COMMITTED]
    assert snap["ab_lock"] == tot[sp.STAT_AB_LOCK]
    assert snap["ab_logic"] == tot[sp.STAT_AB_LOGIC]
    assert snap["lock_requests"] == \
        snap["lock_granted"] + snap["lock_rejected"]


def test_parity_counters_generic_vs_dense():
    """Same seed -> same cohorts: at a low-contention parity geometry
    (exact CF locks draw no hash-conflation conflicts, same property the
    dense-vs-generic stats parity test pins) the engine-independent
    counter subset must be bit-identical between the dense and the
    generic sort-based engine — and the generic engine's counters must
    reconcile with its own stats vector."""
    from dint_tpu.clients import tatp_client as tc
    from dint_tpu.engines import tatp_dense as td
    from dint_tpu.engines import tatp_pipeline as tp

    blocks, seed = 2, 0

    db = td.populate(np.random.default_rng(seed), N_SUB, val_words=VW)
    run_d, init_d, drain_d = _td_build(True)
    carry_d = init_d(db)

    shards, _ = tc.populate_shards(np.random.default_rng(seed), N_SUB,
                                   val_words=VW, log_capacity=1 << 14)
    run_g, init_g, drain_g = _tp_build(True)
    carry_g = init_g(tp.stack_shards(shards))

    tot_g = np.zeros(tp.N_STATS, np.int64)
    for i in range(blocks):
        carry_d, _ = run_d(carry_d, jax.random.fold_in(KEY(seed), i))
        carry_g, s_g = run_g(carry_g, jax.random.fold_in(KEY(seed), i))
        tot_g += np.asarray(s_g, np.int64).sum(axis=0)
    _, _, cnt_d = drain_d(carry_d)
    _, tail_g, cnt_g = drain_g(carry_g)
    tot_g += np.asarray(tail_g, np.int64).sum(axis=0)
    snap_d, snap_g = M.snapshot(cnt_d), M.snapshot(cnt_g)

    # generic engine reconciles against its own stats vector
    assert snap_g["txn_attempted"] == tot_g[tp.STAT_ATTEMPTED]
    assert snap_g["txn_committed"] == tot_g[tp.STAT_COMMITTED]
    assert snap_g["ab_lock"] == tot_g[tp.STAT_AB_LOCK]
    assert snap_g["ab_validate"] == tot_g[tp.STAT_AB_VALIDATE]

    par_d = {n: snap_d[n] for n in mc.PARITY_NAMES}
    par_g = {n: snap_g[n] for n in mc.PARITY_NAMES}
    assert par_d == par_g, (par_d, par_g)
    assert par_d["txn_committed"] > 0 and par_d["install_writes"] > 0


# --------------------------------------------------------- sharded paths


def test_dense_sharded_counters_sum_across_shards():
    from dint_tpu.engines import tatp_dense as td
    from dint_tpu.parallel import dense_sharded as ds

    mesh = ds.make_mesh(4)
    run, init, drain = ds.build_sharded_pipelined_runner(
        mesh, 4, 4 * 200, w=32, val_words=4, cohorts_per_block=2,
        monitor=True)
    carry = init(ds.create_sharded(mesh, 4, 4 * 200, val_words=4,
                                   log_capacity=128))
    tot = np.zeros(td.N_STATS, np.int64)
    for i in range(3):
        carry, s = run(carry, jax.random.fold_in(KEY(2), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    # per-device planes are live mid-run too (stacked [D, N] in the carry)
    per_dev = np.asarray(carry[-1].buf)
    assert per_dev.shape == (4, mc.N_COUNTERS)
    assert (per_dev[:, mc.CTR_STEPS] == per_dev[0, mc.CTR_STEPS]).all()
    _, tail, cnt = drain(carry)
    tot += np.asarray(tail, np.int64).sum(axis=0)
    snap = M.snapshot(cnt)      # sums flows / maxes gauges over devices
    assert snap["txn_attempted"] == tot[td.STAT_ATTEMPTED]
    assert snap["txn_committed"] == tot[td.STAT_COMMITTED]
    assert snap["ab_lock"] == tot[td.STAT_AB_LOCK]
    assert snap["ab_missing"] == tot[td.STAT_AB_MISSING]
    assert snap["ab_validate"] == tot[td.STAT_AB_VALIDATE]
    # every install is pushed over BOTH ppermute hops (CommitBck x2)
    assert snap["repl_push_hop1"] == snap["install_writes"] > 0
    assert snap["repl_push_hop2"] == snap["install_writes"]


def test_dense_sharded_sb_counters_sum_across_shards():
    from dint_tpu.parallel import dense_sharded_sb as dsb

    mesh = dsb.make_mesh(4)
    run, init, drain = dsb.build_sharded_sb_runner(
        mesh, 4, 4 * 128, w=32, cohorts_per_block=2, monitor=True)
    carry = init(dsb.create_sharded_sb(mesh, 4, 4 * 128))
    tot = np.zeros(dsb.N_STATS, np.int64)
    for i in range(3):
        carry, s = run(carry, jax.random.fold_in(KEY(3), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    _, tail, cnt = drain(carry)
    tot += np.asarray(tail, np.int64).sum(axis=0)
    snap = M.snapshot(cnt)
    assert snap["txn_attempted"] == tot[dsb.STAT_ATTEMPTED]
    assert snap["txn_committed"] == tot[dsb.STAT_COMMITTED]
    assert snap["ab_lock"] == tot[dsb.STAT_AB_LOCK]
    assert snap["ab_logic"] == tot[dsb.STAT_AB_LOGIC]
    assert snap["route_overflow"] == tot[dsb.STAT_OVERFLOW]
    assert snap["repl_push_hop1"] == snap["install_writes"] > 0
    assert snap["repl_push_hop2"] == snap["install_writes"]


# ------------------------------------------------------------ trace layer


def test_trace_writer_schema_and_summary(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with M.TraceWriter(p, meta={"name": "t"}) as w:
        d = dict(mc.zeros_dict(), txn_attempted=128, txn_committed=100,
                 ring_hwm=7)
        w.wave(step=0, t=0.0, dur_s=0.5, batch=128, counters=d)
        w.wave(step=1, t=0.5, dur_s=0.5, batch=128, counters=d)
        w.wave(step=2, t=1.0, dur_s=0.5, batch=128, counters=None)
    meta, waves = M.read_events(p)
    assert meta["schema"] == 1 and meta["counters"] == list(mc.ALL_NAMES)
    assert len(waves) == 3
    # schema-stable: every registered name present on monitored waves,
    # explicit null on unmonitored ones
    assert set(waves[0]["counters"]) == set(mc.ALL_NAMES)
    assert waves[2]["counters"] is None
    from dint_tpu.monitor.trace import summarize_events
    s = summarize_events(meta, waves)
    assert s["monitored_waves"] == 2
    assert s["counters"]["txn_attempted"] == 256    # flows sum
    assert s["counters"]["ring_hwm"] == 7           # gauges max
    assert s["abort_rate"] == pytest.approx(1 - 200 / 256)


def test_monitor_observe_and_chrome_export(tmp_path):
    from dint_tpu.engines import tatp_dense as td

    p = str(tmp_path / "run.jsonl")
    db = td.populate(np.random.default_rng(0), N_SUB, val_words=VW)
    run, init, drain = _td_build(True)
    carry = init(db)
    with M.TraceWriter(p, meta={"name": "test"}) as writer:
        monitor = M.Monitor(writer)
        for i in range(3):
            carry, _ = run(carry, jax.random.fold_in(KEY(0), i))
            monitor.observe(carry[-1], batch=CPB * W, dur_s=0.01)
    _, _, cnt = drain(carry)
    snap = M.snapshot(cnt)
    # the per-wave deltas sum to the pre-drain totals: outcomes count at
    # cohort COMPLETION, 2 steps behind dispatch in the 3-stage pipeline
    assert monitor.totals["txn_attempted"] == (3 * CPB - 2) * W
    # the drain flushes the 2 in-flight cohorts into the final snapshot
    assert snap["txn_attempted"] == 3 * CPB * W
    out = str(tmp_path / "trace.json")
    n = M.export_chrome_trace(p, out)
    with open(out) as f:
        tr = json.load(f)
    assert n == len(tr["traceEvents"]) > 3
    assert any(e.get("ph") == "X" for e in tr["traceEvents"])
    assert any(e.get("ph") == "C" for e in tr["traceEvents"])


def test_monitor_deferred_drain_deltas_bit_identical(tmp_path):
    """The dintscope double-buffered drain (observe(defer=True): block
    i-1's ~100-byte fetch materializes only after block i dispatched, via
    an on-device copy that survives the carry donation) must emit the
    SAME wave-event counter deltas as the synchronous path — only WHEN
    the bytes cross to the host changes, never what they say."""
    from dint_tpu.engines import tatp_dense as td

    def run_stream(defer):
        p = str(tmp_path / f"run_{int(defer)}.jsonl")
        db = td.populate(np.random.default_rng(0), N_SUB, val_words=VW)
        run, init, drain = _td_build(True)
        carry = init(db)
        with M.TraceWriter(p, meta={"name": "defer_pin"}) as writer:
            monitor = M.Monitor(writer)
            for i in range(4):
                carry, _ = run(carry, jax.random.fold_in(KEY(0), i))
                monitor.observe(carry[-1], batch=CPB * W, dur_s=0.01,
                                defer=defer)
            last = monitor.flush()   # lands the deferred final window
            assert (last is None) == (not defer)
        _, waves_ev = M.read_events(p)
        return waves_ev, monitor.totals

    sync_waves, sync_totals = run_stream(False)
    defr_waves, defr_totals = run_stream(True)
    assert len(sync_waves) == len(defr_waves) == 4
    for a, b in zip(sync_waves, defr_waves):
        assert a["step"] == b["step"] and a["batch"] == b["batch"]
        assert a["counters"] == b["counters"]
    assert sync_totals == defr_totals


@pytest.mark.slow  # ~11s; error-path edge, not an identity pin
def test_profiler_session_noop_and_bad_dir(tmp_path):
    from dint_tpu.monitor.trace import profiler_session

    with profiler_session(None) as info:
        assert info["trace_dir"] is None
    # a profiler failure must not raise out of the context
    with profiler_session(str(tmp_path / "t1")) as info:
        pass


# ------------------------------------------------------------------- CLI


def test_dintmon_cli_json_subprocess(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with M.TraceWriter(p, meta={"name": "cli"}) as w:
        d = dict(mc.zeros_dict(), txn_attempted=64, txn_committed=60,
                 lock_requests=10, lock_granted=10, ring_hwm=3)
        w.wave(step=0, t=0.0, dur_s=1.0, batch=64, counters=d)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    c = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintmon.py"),
         "summarize", p, "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert c.returncode == 0, c.stderr
    out = json.loads(c.stdout.strip().splitlines()[-1])
    assert out["counters"]["txn_attempted"] == 64
    assert out["rates_per_s"]["txn_committed"] == 60.0

    # artifact mode: a bench.py-style JSON object with counters: null
    art = tmp_path / "BENCH_x.json"
    art.write_text(json.dumps({"metric": "m", "counters": None,
                               "window_s": 1.0}))
    c = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintmon.py"),
         "summarize", str(art), "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert c.returncode == 0, c.stderr
    assert json.loads(c.stdout)["counters"] is None

    # diff + describe stay parseable
    c = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintmon.py"),
         "diff", p, p, "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert c.returncode == 0, c.stderr
    rows = json.loads(c.stdout)["rows"]
    assert all(r["delta"] == 0 for r in rows) and rows
    c = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintmon.py"),
         "describe", "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert c.returncode == 0, c.stderr
    desc = json.loads(c.stdout)
    assert len(desc["counters"]) == mc.N_COUNTERS
