"""dintscan ordered run (tables/run.py): snapshot, overlay, rebuild and
scan-merge unit tests. The differential and serial-order tests against
the store engine live in test_store.py; these pin the run's own
invariants — sortedness, latest-wins dedupe, tombstone shadowing, the
stale contract and the locate lower bound — directly."""
import jax.numpy as jnp
import numpy as np
import pytest

from dint_tpu.ops import pallas_gather as pg
from dint_tpu.tables import kv, run as run_mod

VW = 4
U32 = jnp.uint32


def mk_table(rng, keys, n_buckets=1 << 8):
    keys = np.asarray(keys, np.uint64)
    vals = rng.integers(0, 1 << 32, size=(len(keys), VW), dtype=np.uint32)
    table = kv.create(n_buckets, slots=8, val_words=VW)
    return kv.populate(table, keys, vals), vals


def append(run, keys, vals, tomb=None, ver=None, mask=None):
    """delta_append with u64 host keys < 2**32 (hi word zero)."""
    keys = np.asarray(keys, np.uint64)
    r = len(keys)
    vals = np.asarray(vals, np.uint32).reshape(r, VW)
    return run_mod.delta_append(
        run,
        jnp.zeros((r,), U32), jnp.asarray(keys.astype(np.uint32)),
        jnp.asarray(np.ones(r) if ver is None else ver, U32),
        jnp.asarray(vals.reshape(-1)),
        jnp.asarray(np.zeros(r, bool) if tomb is None else tomb),
        jnp.asarray(np.ones(r, bool) if mask is None else mask))


def run_keys(run):
    n = int(run.n)
    return np.asarray(run.key_lo)[:n].astype(np.uint64)


def test_from_table_sorted_dense_snapshot(rng):
    keys = rng.choice(10_000, size=200, replace=False)
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=16)
    assert int(run.n) == 200
    got = run_keys(run)
    assert np.array_equal(got, np.sort(keys))
    # rows past n hold the PAD key so binary search needs no bounds
    assert (np.asarray(run.key_hi)[200:] == 0xFFFFFFFF).all()
    assert (np.asarray(run.key_lo)[200:] == 0xFFFFFFFF).all()
    # merged view == the authoritative table's view
    assert run_mod.to_items(run) == kv.to_dict(table)


def test_locate_is_lower_bound(rng):
    keys = np.sort(rng.choice(5_000, size=100, replace=False))
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=8)
    q = np.concatenate([keys, keys + 1, keys - 1,
                        np.array([0, 4_999, 10_000])]).astype(np.uint64)
    pos = np.asarray(run_mod.locate(
        run, jnp.zeros(len(q), U32), jnp.asarray(q.astype(np.uint32))))
    want = np.searchsorted(run_keys(run), q, side="left")
    assert np.array_equal(pos, want)


def test_delta_append_latest_wins_and_dedupes(rng):
    table, _ = mk_table(rng, [10, 20, 30])
    run = run_mod.from_table(table, delta_cap=8)
    v1 = rng.integers(0, 1 << 32, size=(1, VW), dtype=np.uint32)
    v2 = rng.integers(0, 1 << 32, size=(1, VW), dtype=np.uint32)
    run = append(run, [20], v1, ver=[5])
    run = append(run, [20], v2, ver=[6])      # same key, later batch
    assert int(run.d_n) == 1                  # deduped, latest wins
    items = run_mod.to_items(run)
    assert items[20] == (tuple(int(x) for x in v2[0]), 6)
    # within ONE batch the overlay keeps the masked writes it was given
    run2 = run_mod.from_table(table, delta_cap=8)
    run2 = append(run2, [40, 50], np.vstack([v1, v2]),
                  mask=np.array([True, False]))
    assert int(run2.d_n) == 1                 # masked lane never lands
    assert 50 not in run_mod.to_items(run2)


def test_tombstone_shadows_run_row(rng):
    table, _ = mk_table(rng, [1, 2, 3, 4])
    run = run_mod.from_table(table, delta_cap=8)
    run = append(run, [2], np.zeros((1, VW), np.uint32),
                 tomb=np.array([True]))
    items = run_mod.to_items(run)
    assert 2 not in items and set(items) == {1, 3, 4}
    # rebuild folds the tombstone: the row is gone from the dense run
    rb = run_mod.rebuild_run(run)
    assert int(rb.n) == 3 and int(rb.d_n) == 0
    assert np.array_equal(run_keys(rb), [1, 3, 4])


def test_rebuild_matches_merged_view(rng):
    keys = rng.choice(1_000, size=60, replace=False)
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=16)
    # upserts on existing + new keys, one delete
    up = rng.choice(keys, size=5, replace=False)
    new = np.array([2_001, 2_002, 2_003])
    vals = rng.integers(0, 1 << 32, size=(9, VW), dtype=np.uint32)
    run = append(run, np.concatenate([up, new, up[:1]]), vals,
                 tomb=np.array([False] * 8 + [True]))
    want = run_mod.to_items(run)              # merged run ∪ delta
    rb = run_mod.rebuild_run(run)
    assert run_mod.to_items(rb) == want
    assert int(rb.d_n) == 0 and not bool(rb.stale)
    assert np.array_equal(run_keys(rb), np.sort(run_keys(rb)))


def test_overlay_overflow_sets_stale_and_refresh_recovers(rng):
    keys = rng.choice(1_000, size=40, replace=False)
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=4)
    new = np.arange(3_000, 3_006, dtype=np.uint64)   # 6 > delta_cap
    run = append(run, new, rng.integers(0, 1 << 32, size=(6, VW),
                                        dtype=np.uint32))
    assert bool(run.stale)
    # stale == overlay dropped writes: the run CANNOT be repaired from
    # itself; refresh re-snapshots from the authoritative table
    fresh = run_mod.refresh(table, run)
    assert not bool(fresh.stale) and int(fresh.d_n) == 0
    assert run_mod.to_items(fresh) == kv.to_dict(table)


def test_refresh_branches_agree_on_intact_overlay(rng):
    """refresh's two branches (merge-compact vs re-snapshot) must build
    identical runs when the overlay is intact AND the table saw the same
    writes — `stale` only ever trades compute."""
    keys = rng.choice(1_000, size=30, replace=False)
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=8)
    up = rng.choice(keys, size=4, replace=False)
    vals = rng.integers(0, 1 << 32, size=(4, VW), dtype=np.uint32)
    run = append(run, up, vals, ver=[7, 7, 7, 7])
    table = kv.populate(kv.create(1 << 8, slots=8, val_words=VW),
                        *_items_to_arrays(run_mod.to_items(run)))
    a = run_mod.rebuild_run(run)
    b = run_mod.from_table(table, delta_cap=8)
    assert run_mod.to_items(a) == run_mod.to_items(b)
    assert np.array_equal(run_keys(a), run_keys(b))


def _items_to_arrays(items):
    keys = np.array(sorted(items), np.uint64)
    vals = np.array([items[int(k)][0] for k in keys], np.uint32)
    vers = np.array([items[int(k)][1] for k in keys], np.uint32)
    return keys, vals, vers


def _scan_oracle(items, start, slen):
    rows = sorted((k, v) for k, v in items.items() if k >= start)
    return rows[:slen]


@pytest.mark.parametrize("use_pallas", [False, True])
def test_merge_scan_matches_sorted_view(rng, use_pallas):
    """locate → slab gather (either route) → merge_scan == the first
    slen live keys >= start of the merged dict, in key order."""
    scan_max, dcap = 6, 4
    keys = rng.choice(200, size=50, replace=False)
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=dcap)
    up = rng.choice(keys, size=2, replace=False)
    vals = rng.integers(0, 1 << 32, size=(3, VW), dtype=np.uint32)
    run = append(run, np.concatenate([up, up[:1]]), vals,
                 tomb=np.array([False, False, True]))
    items = run_mod.to_items(run)

    starts = rng.integers(0, 220, size=16).astype(np.uint64)
    slens = rng.integers(0, scan_max + 1, size=16)
    lg = scan_max + dcap
    q_hi = jnp.zeros(16, U32)
    q_lo = jnp.asarray(starts.astype(np.uint32))
    off = jnp.clip(run_mod.locate(run, q_hi, q_lo), 0, run.cap - lg)
    s_hi, s_lo, s_ver, s_val = pg.scan_slab(
        run.key_hi, run.key_lo, run.ver, run.val, off, lg, VW,
        use_pallas=use_pallas)
    count, k_hi, k_lo, k_ver, k_val, d_hits = run_mod.merge_scan(
        run, s_hi, s_lo, s_ver, s_val, off, q_hi, q_lo,
        jnp.asarray(slens, jnp.int32), scan_max)
    count = np.asarray(count)
    k_lo, k_ver = np.asarray(k_lo), np.asarray(k_ver)
    k_val = np.asarray(k_val)
    for i in range(16):
        want = _scan_oracle(items, int(starts[i]), int(slens[i]))
        assert count[i] == len(want), (i, starts[i], slens[i])
        for j, (k, (v, ver)) in enumerate(want):
            assert int(k_lo[i, j]) == k
            assert int(k_ver[i, j]) == ver
            assert tuple(int(x) for x in k_val[i, j]) == v
        # rows past count are zeroed (the reply-slab contract)
        assert (k_lo[i, count[i]:] == 0).all()
        assert (k_ver[i, count[i]:] == 0).all()
    assert (np.asarray(d_hits) <= count).all()


def test_scan_slab_routes_bit_identical(rng):
    """The probe-and-degrade contract: the streaming kernel and the XLA
    slab gather return bit-identical windows for in-bounds offsets."""
    keys = rng.choice(500, size=80, replace=False)
    table, _ = mk_table(rng, keys)
    run = run_mod.from_table(table, delta_cap=8)
    lg = 12
    off = jnp.asarray(rng.integers(0, run.cap - lg, size=16), jnp.int32)
    a = pg.scan_slab(run.key_hi, run.key_lo, run.ver, run.val, off, lg,
                     VW, use_pallas=False)
    b = pg.scan_slab(run.key_hi, run.key_lo, run.ver, run.val, off, lg,
                     VW, use_pallas=True)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_locate_bits_matches_formula():
    # lg in the dint.store.scan_locate wave formula == locate rounds
    assert run_mod.locate_bits(64) == 7
    assert run_mod.locate_bits(1) == 1
    for cap in (2, 3, 64, 100, 1 << 16):
        assert run_mod.locate_bits(cap) == int(cap).bit_length()
