"""Sort-free dense TATP engine: semantics vs the generic pipelined engine."""
import jax
import numpy as np

from dint_tpu.clients import tatp_client as tc
from dint_tpu.engines import tatp, tatp_dense as td, tatp_pipeline as tp
from dint_tpu.tables import log as logring

VW = 4


def _run(n_sub, w, blocks, cohorts_per_block=2, seed=0, mix=None):
    db = td.populate(np.random.default_rng(seed), n_sub, val_words=VW)
    run, init, drain = td.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=cohorts_per_block,
        mix=mix)
    carry = init(db)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    db, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return db, total


def test_contention_fires_validate_aborts():
    # same forced US/IC-heavy mix over a tiny keyspace as the generic
    # pipelined engine's test: in-flight cohorts commit sf rows between a
    # younger cohort's read and its validate
    mix = np.array([0, 0, 0, 50, 0, 50, 0], np.float64) / 100.0
    db, total = _run(n_sub=32, w=256, blocks=4, mix=mix)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    assert attempted == 4 * 2 * 256
    assert committed > 0
    assert int(total[td.STAT_MAGIC_BAD]) == 0
    assert int(total[td.STAT_AB_VALIDATE]) > 0
    assert int(total[td.STAT_AB_LOCK]) > 0
    outcomes = (committed + int(total[td.STAT_AB_LOCK])
                + int(total[td.STAT_AB_MISSING])
                + int(total[td.STAT_AB_VALIDATE]))
    assert outcomes == attempted


def test_low_contention_mostly_commits():
    db, total = _run(n_sub=20_000, w=64, blocks=3)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    # abort rate ~= the analytic ab_missing floor (~25%, see
    # test_ab_missing_matches_population_analytics — TATP's read txns
    # fail on absent rows BY DESIGN) + ~0 contention
    assert 1 - committed / attempted < 0.30
    contention = int(total[td.STAT_AB_LOCK]) + int(total[td.STAT_AB_VALIDATE])
    assert contention / attempted < 0.01, total
    assert int(total[td.STAT_MAGIC_BAD]) == 0


def test_ab_missing_matches_population_analytics():
    """VERDICT #9: ab_missing dominates the abort mix — prove it is
    workload semantics, not a lookup bug, by pinning observed rates to the
    analytic expectations of the population rules + txn mix:

      P(ai/sf present)  p_sf = 0.625 + 0.375^4/4   (the >=1-per-sub fix)
      P(cf present)     p_cf = p_sf * 0.25
      GET_ACCESS   (35%) misses at 1 - p_sf          (ai row required)
      GET_NEW_DEST (10%) misses at 1 - p_cf          (sf AND cf required)
      UPDATE_SUB    (2%) misses at 1 - p_sf          (sub always present)
      INSERT_CF     (2%) misses at 1 - p_sf*0.75     (cf must NOT exist)
      DELETE_CF     (2%) misses at 1 - p_cf          (cf must exist)
      others        (49%) never miss

    Few blocks over a fresh populate so insert/delete drift of CF
    occupancy stays negligible."""
    n_sub, w, blocks = 50_000, 1024, 3
    _, total = _run(n_sub=n_sub, w=w, blocks=blocks, seed=11)
    attempted = int(total[td.STAT_ATTEMPTED])
    observed = int(total[td.STAT_AB_MISSING]) / attempted

    p_sf = 0.625 + 0.375 ** 4 / 4
    p_cf = p_sf * 0.25
    expected = (0.35 * (1 - p_sf)
                + 0.10 * (1 - p_cf)
                + 0.02 * (1 - p_sf)
                + 0.02 * (1 - p_sf * 0.75)
                + 0.02 * (1 - p_cf))
    # binomial sd at n=attempted is ~0.3%; allow drift + NURand skew
    assert abs(observed - expected) < 0.01, (observed, expected)


def test_drain_releases_locks_and_log_replicas_converge():
    db, _ = _run(n_sub=64, w=128, blocks=3, seed=3)
    assert not np.asarray(db.locked).any()
    # log x3 (the physically replicated artifact): slots bit-identical
    r0 = np.asarray(logring.replica_entries(db.log, 0))
    assert np.array_equal(r0, np.asarray(logring.replica_entries(db.log, 1)))
    assert np.array_equal(r0, np.asarray(logring.replica_entries(db.log, 2)))
    # sentinel row untouched
    assert not bool(np.asarray(db.exists)[-1])
    assert int(np.asarray(db.ver)[-1]) == 0


def test_delete_only_mix_empties_cf():
    # DELETE_CF-only mix over a tiny keyspace: every present CF row is
    # eventually deleted; deletes log is_del entries and bump versions
    mix = np.array([0, 0, 0, 0, 0, 0, 100], np.float64) / 100.0
    n_sub = 4
    db0 = td.populate(np.random.default_rng(0), n_sub, val_words=VW)
    cf0 = np.asarray(db0.exists)[10 * (n_sub + 1):-1]
    assert cf0.any()
    db, total = _run(n_sub=n_sub, w=128, blocks=6, mix=mix)
    cf1 = np.asarray(db.exists)[10 * (n_sub + 1):-1]
    assert not cf1.any()
    assert int(total[td.STAT_COMMITTED]) == int(cf0.sum())
    # committed deletes bumped their rows' versions past populate's 1
    vers = np.asarray(db.ver)[10 * (n_sub + 1):-1]
    assert (vers[cf0] >= 2).all()


def test_insert_mix_fills_cf_and_versions_are_monotonic():
    mix = np.array([0, 0, 0, 0, 0, 100, 0], np.float64) / 100.0
    n_sub = 4
    db0 = td.populate(np.random.default_rng(0), n_sub, val_words=VW)
    cf0 = np.asarray(db0.exists)[10 * (n_sub + 1):-1].sum()
    db, total = _run(n_sub=n_sub, w=128, blocks=6, mix=mix)
    cf1 = np.asarray(db.exists)[10 * (n_sub + 1):-1].sum()
    assert int(total[td.STAT_COMMITTED]) == cf1 - cf0
    assert int(total[td.STAT_MAGIC_BAD]) == 0


def test_rebase_stamps_preserves_lock_state():
    """rebase_stamps fires only after ~12k steps on hardware; pin its
    remap directly: live stamps (step-1 held, step-2 expiring) keep their
    held/free meaning and slot fields, older stamps zero."""
    n_sub = 8
    db = td.populate(np.random.default_rng(0), n_sub, val_words=VW)
    t = np.uint32(td.REBASE_AT + 7)
    arb = np.zeros(td.n_rows(n_sub) + 1, np.uint32)
    arb[3] = ((t - 1) << td.K_ARB) | 11       # held (stamped last step)
    arb[5] = ((t - 2) << td.K_ARB) | 22       # expiring this step
    arb[7] = ((t - 3) << td.K_ARB) | 33       # stale
    db = db.replace(arb=jax.numpy.asarray(arb),
                    step=jax.numpy.asarray(t, jax.numpy.uint32))
    held_before = np.asarray(db.locked)

    db2 = td.rebase_stamps(db)
    assert int(np.asarray(db2.step)) == 3
    arb2 = np.asarray(db2.arb)
    assert np.array_equal(np.asarray(db2.locked), held_before)
    assert arb2[3] == (2 << td.K_ARB) | 11    # held -> step 2, slot kept
    assert arb2[5] == (1 << td.K_ARB) | 22    # expiring -> step 1
    assert arb2[7] == 0                       # stale zeroed
    assert (arb2[np.arange(len(arb2)) % 2 == 0] == 0).all()

    # and the engine keeps running correctly from a rebased state: the
    # next steps' grants/stats still close
    run, init, drain = td.build_pipelined_runner(n_sub, w=16, val_words=VW,
                                                 cohorts_per_block=2)
    carry = init(db)
    carry, s = run(carry, jax.random.PRNGKey(0))
    tot = np.asarray(s, np.int64).sum(axis=0)
    _, tail = drain(carry)
    tot += np.asarray(tail, np.int64).sum(axis=0)
    outcomes = (tot[td.STAT_COMMITTED] + tot[td.STAT_AB_LOCK]
                + tot[td.STAT_AB_MISSING] + tot[td.STAT_AB_VALIDATE])
    assert outcomes == tot[td.STAT_ATTEMPTED]
    assert int(tot[td.STAT_MAGIC_BAD]) == 0


def test_populate_device_matches_population_rules():
    """On-device populate (the 7M-scale path) obeys the same population
    rules as the numpy path (client_ebpf_shard.cc:96-341): subscribers all
    present, ai/sf ~0.625 with >=1 per subscriber, CF ~25% of present sf
    slots, payload/magic/meta wiring identical."""
    n_sub = 500
    p1 = n_sub + 1
    db = td.populate_device(jax.random.PRNGKey(0), n_sub, val_words=VW)
    ex = np.asarray(db.exists)
    meta = np.asarray(db.meta)
    val = np.asarray(db.val).reshape(-1, VW)
    base = td._bases(p1)

    assert ex[base[0] + 1: base[0] + p1].all() and not ex[0]
    assert ex[base[1] + 1: base[1] + p1].all() and not ex[base[1]]
    assert not ex[-1]
    sf = ex[base[3]:base[3] + 4 * p1].reshape(p1, 4)
    assert not sf[0].any()
    assert sf[1:].any(axis=1).all()              # >=1 sf_type each
    assert 0.57 < sf[1:].mean() < 0.69           # p=0.625 (+ the >=1 fix)
    cf = ex[base[4]:-1].reshape(p1, 4, 3)
    assert not cf[~sf].any()                     # CF only under present sf
    assert 0.19 < cf[sf].mean() < 0.31           # p=0.25
    rows = np.nonzero(ex[:-1])[0]
    region = np.searchsorted(base, rows, side="right") - 1
    assert (val[rows, 0] == rows - base[region]).all()
    assert (val[rows, 1] == td.MAGIC).all()
    assert (meta[rows] >> 1 == 1).all()          # populate version 1
    absent = np.nonzero(~ex[:-1])[0]
    assert (val[absent] == 0).all() and (meta[absent] == 0).all()

    # and the engine runs clean on it
    run, init, drain = td.build_pipelined_runner(
        n_sub, w=64, val_words=VW, cohorts_per_block=2)
    carry = init(db)
    carry, stats = run(carry, jax.random.PRNGKey(1))
    total = np.asarray(stats, np.int64).sum(axis=0)
    _, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    assert int(total[td.STAT_MAGIC_BAD]) == 0
    assert int(total[td.STAT_COMMITTED]) > 0


def test_matches_generic_pipelined_engine_at_low_contention():
    """Same seed -> same population + same cohorts; at low contention the
    dense engine must produce the exact same stats as the generic
    sort-based engine (engines/tatp_pipeline): exact CF locks only remove
    hash-conflation conflicts, so the seed must draw none. Seed 7 draws
    exactly one (the generic engine conflates two CF keys into one lock
    row and aborts a txn the dense engine correctly commits — seeds 0-3
    draw zero); the test ran broken on that seed since the seed drop."""
    n_sub, w, blocks, seed = 2000, 256, 2, 0

    db = td.populate(np.random.default_rng(seed), n_sub, val_words=VW)
    run_d, init_d, drain_d = td.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=2)
    carry = init_d(db)

    shards, _ = tc.populate_shards(np.random.default_rng(seed), n_sub,
                                   val_words=VW, log_capacity=1 << 14)
    stacked = tp.stack_shards(shards)
    run_g, init_g, drain_g = tp.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=2)
    carry_g = init_g(stacked)

    key = jax.random.PRNGKey(seed)
    tot_d = np.zeros(td.N_STATS, np.int64)
    tot_g = np.zeros(tp.N_STATS, np.int64)
    for i in range(blocks):
        carry, s_d = run_d(carry, jax.random.fold_in(key, i))
        carry_g, s_g = run_g(carry_g, jax.random.fold_in(key, i))
        tot_d += np.asarray(s_d, np.int64).sum(axis=0)
        tot_g += np.asarray(s_g, np.int64).sum(axis=0)
    db, tail_d = drain_d(carry)
    stacked, tail_g = drain_g(carry_g)
    tot_d += np.asarray(tail_d, np.int64).sum(axis=0)
    tot_g += np.asarray(tail_g, np.int64).sum(axis=0)

    assert tot_d.tolist() == tot_g.tolist(), (tot_d, tot_g)

    # table end-states agree too: dense flat rows vs the generic engine's
    # per-table arrays (dense tables only; CF layouts differ by design)
    p1 = n_sub + 1
    base = td._bases(p1)
    ver_d = np.asarray(db.ver)
    for tid, t in ((tatp.SUBSCRIBER, stacked.sub), (tatp.SEC_SUBSCRIBER,
                   stacked.sec), (tatp.ACCESS_INFO, stacked.ai),
                   (tatp.SPECIAL_FACILITY, stacked.sf)):
        n = np.asarray(t.ver).shape[1]
        got = ver_d[base[tid]:base[tid] + n]
        want = np.asarray(t.ver)[0]
        assert np.array_equal(got, want), tid
