"""Sort-free dense TATP engine: semantics vs the generic pipelined engine."""
import jax
import numpy as np

from dint_tpu.clients import tatp_client as tc
from dint_tpu.engines import tatp, tatp_dense as td, tatp_pipeline as tp
from dint_tpu.tables import log as logring

VW = 4


def _run(n_sub, w, blocks, cohorts_per_block=2, seed=0, mix=None):
    db = td.populate(np.random.default_rng(seed), n_sub, val_words=VW)
    run, init, drain = td.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=cohorts_per_block,
        mix=mix)
    carry = init(db)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    db, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return db, total


def test_contention_fires_validate_aborts():
    # same forced US/IC-heavy mix over a tiny keyspace as the generic
    # pipelined engine's test: in-flight cohorts commit sf rows between a
    # younger cohort's read and its validate
    mix = np.array([0, 0, 0, 50, 0, 50, 0], np.float64) / 100.0
    db, total = _run(n_sub=32, w=256, blocks=4, mix=mix)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    assert attempted == 4 * 2 * 256
    assert committed > 0
    assert int(total[td.STAT_MAGIC_BAD]) == 0
    assert int(total[td.STAT_AB_VALIDATE]) > 0
    assert int(total[td.STAT_AB_LOCK]) > 0
    outcomes = (committed + int(total[td.STAT_AB_LOCK])
                + int(total[td.STAT_AB_MISSING])
                + int(total[td.STAT_AB_VALIDATE]))
    assert outcomes == attempted


def test_low_contention_mostly_commits():
    db, total = _run(n_sub=20_000, w=64, blocks=3)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    assert 1 - committed / attempted < 0.12
    contention = int(total[td.STAT_AB_LOCK]) + int(total[td.STAT_AB_VALIDATE])
    assert contention / attempted < 0.01, total
    assert int(total[td.STAT_MAGIC_BAD]) == 0


def test_drain_releases_locks_and_log_replicas_converge():
    db, _ = _run(n_sub=64, w=128, blocks=3, seed=3)
    assert not np.asarray(db.locked).any()
    # log x3 (the physically replicated artifact): slots bit-identical
    r0 = np.asarray(logring.replica_entries(db.log, 0))
    assert np.array_equal(r0, np.asarray(logring.replica_entries(db.log, 1)))
    assert np.array_equal(r0, np.asarray(logring.replica_entries(db.log, 2)))
    # sentinel row untouched
    assert not bool(np.asarray(db.exists)[-1])
    assert int(np.asarray(db.ver)[-1]) == 0


def test_delete_only_mix_empties_cf():
    # DELETE_CF-only mix over a tiny keyspace: every present CF row is
    # eventually deleted; deletes log is_del entries and bump versions
    mix = np.array([0, 0, 0, 0, 0, 0, 100], np.float64) / 100.0
    n_sub = 4
    db0 = td.populate(np.random.default_rng(0), n_sub, val_words=VW)
    cf0 = np.asarray(db0.exists)[10 * (n_sub + 1):-1]
    assert cf0.any()
    db, total = _run(n_sub=n_sub, w=128, blocks=6, mix=mix)
    cf1 = np.asarray(db.exists)[10 * (n_sub + 1):-1]
    assert not cf1.any()
    assert int(total[td.STAT_COMMITTED]) == int(cf0.sum())
    # committed deletes bumped their rows' versions past populate's 1
    vers = np.asarray(db.ver)[10 * (n_sub + 1):-1]
    assert (vers[cf0] >= 2).all()


def test_insert_mix_fills_cf_and_versions_are_monotonic():
    mix = np.array([0, 0, 0, 0, 0, 100, 0], np.float64) / 100.0
    n_sub = 4
    db0 = td.populate(np.random.default_rng(0), n_sub, val_words=VW)
    cf0 = np.asarray(db0.exists)[10 * (n_sub + 1):-1].sum()
    db, total = _run(n_sub=n_sub, w=128, blocks=6, mix=mix)
    cf1 = np.asarray(db.exists)[10 * (n_sub + 1):-1].sum()
    assert int(total[td.STAT_COMMITTED]) == cf1 - cf0
    assert int(total[td.STAT_MAGIC_BAD]) == 0


def test_matches_generic_pipelined_engine_at_low_contention():
    """Same seed -> same population + same cohorts; at low contention the
    dense engine must produce the exact same stats as the generic
    sort-based engine (engines/tatp_pipeline): exact CF locks only remove
    hash-conflation conflicts, which are absent at this scale."""
    n_sub, w, blocks, seed = 2000, 256, 2, 7

    db = td.populate(np.random.default_rng(seed), n_sub, val_words=VW)
    run_d, init_d, drain_d = td.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=2)
    carry = init_d(db)

    shards, _ = tc.populate_shards(np.random.default_rng(seed), n_sub,
                                   val_words=VW)
    stacked = tp.stack_shards(shards)
    run_g, init_g, drain_g = tp.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=2)
    carry_g = init_g(stacked)

    key = jax.random.PRNGKey(seed)
    tot_d = np.zeros(td.N_STATS, np.int64)
    tot_g = np.zeros(tp.N_STATS, np.int64)
    for i in range(blocks):
        carry, s_d = run_d(carry, jax.random.fold_in(key, i))
        carry_g, s_g = run_g(carry_g, jax.random.fold_in(key, i))
        tot_d += np.asarray(s_d, np.int64).sum(axis=0)
        tot_g += np.asarray(s_g, np.int64).sum(axis=0)
    db, tail_d = drain_d(carry)
    stacked, tail_g = drain_g(carry_g)
    tot_d += np.asarray(tail_d, np.int64).sum(axis=0)
    tot_g += np.asarray(tail_g, np.int64).sum(axis=0)

    assert tot_d.tolist() == tot_g.tolist(), (tot_d, tot_g)

    # table end-states agree too: dense flat rows vs the generic engine's
    # per-table arrays (dense tables only; CF layouts differ by design)
    p1 = n_sub + 1
    base = td._bases(p1)
    ver_d = np.asarray(db.ver)
    for tid, t in ((tatp.SUBSCRIBER, stacked.sub), (tatp.SEC_SUBSCRIBER,
                   stacked.sec), (tatp.ACCESS_INFO, stacked.ai),
                   (tatp.SPECIAL_FACILITY, stacked.sf)):
        n = np.asarray(t.ver).shape[1]
        got = ver_d[base[tid]:base[tid] + n]
        want = np.asarray(t.ver)[0]
        assert np.array_equal(got, want), tid
