"""dintcal: the calibration & prediction-audit plane (ISSUE 18).

The acceptance pins, per ISSUE.md:
  * `dintcal fit` on the checked-in evidence fixture reproduces the
    pinned CALIB.json coefficients bit-for-bit (the closed-form least
    squares is deterministic pure-python arithmetic);
  * `dintcal check` exits 1 NAMING the drifted wave or coefficient on
    injected drift, 0 on the clean fixture;
  * the controller decision journal is a pure function of (schedule,
    seed) under VirtualClock — two runs give byte-identical journals —
    and its shed entries reconcile exactly with the dintmon
    serve_shed_lanes counter;
  * `dintcal audit` replays every recorded width/shed/hot_frac decision
    through the pure policy functions; a hand-tampered decision fails
    the audit naming the entry and block;
  * the calib_check pass fails closed on hand-edited coefficients
    (unfit-model), broken provenance, unregistered waves, and
    plan-vs-calib model drift — and PLAN.json's serve rows record which
    model priced them (source + hash).

Fixtures regenerate with `python tools/dintcal.py synth`.
"""
import copy
import importlib.util
import json
import os
import subprocess
import sys

import pytest

from dint_tpu.monitor import calib as CAL
from dint_tpu.serve import (ControllerCfg, ServeEngine, ServiceModel,
                            VirtualClock, WidthController,
                            constant_schedule)

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVIDENCE_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                                "dintcal_evidence.json")
JOURNAL_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                               "dintcal_journal.jsonl")
CALIB_PINNED = os.path.join(REPO, "CALIB.json")

REGEN = "regenerate them: python tools/dintcal.py synth"


def _cli_main():
    """The tools/dintcal.py entry point, loaded in-process (argv-driven,
    same exit codes as the subprocess — without a fresh jax import per
    invocation)."""
    spec = importlib.util.spec_from_file_location(
        "dintcal_cli", os.path.join(REPO, "tools", "dintcal.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


# ------------------------------------------------------- closed-form fit


def test_fit_closed_form_exact_on_linear_samples():
    """Samples exactly on a line recover its coefficients exactly (the
    normal equations are pure float arithmetic, rounded to 6 dp)."""
    m = ServiceModel(base_us=200.0, per_lane_ns=25.0)
    samples = [[w, m.service_us(w)] for w in (64, 256, 1024, 4096)]
    fit = CAL.fit_service_model(samples)
    assert fit["base_us"] == 200.0
    assert fit["per_lane_ns"] == 25.0
    assert fit["rms_us"] == 0.0 and fit["max_abs_us"] == 0.0
    assert fit["n"] == 4 and fit["widths"] == [64, 256, 1024, 4096]


def test_fit_requires_two_distinct_widths():
    """One width cannot separate the floor from the slope."""
    with pytest.raises(ValueError, match="distinct widths"):
        CAL.fit_service_model([[256, 160.0], [256, 161.0]])
    with pytest.raises(ValueError, match="distinct widths"):
        CAL.fit_service_model([])


def test_implied_gbps_is_the_reconciliation_unit():
    # 1 MB in 1 ms == 1 GB/s
    assert CAL.implied_gbps(1.0, 1e6) == pytest.approx(1.0)


# ------------------------------------------------- fixtures: drift guard


def test_evidence_fixture_matches_fresh_synth():
    """The checked-in evidence fixture must equal a fresh deterministic
    synthesis — any drift means the synthesizer (or the wave formulas it
    prices with) changed without re-pinning the fixture."""
    with open(EVIDENCE_FIXTURE) as fh:
        pinned = json.load(fh)
    assert pinned == CAL.synthesize_evidence(), (
        "tests/fixtures/dintcal_evidence.json drifted from "
        f"synthesize_evidence() — {REGEN}")


def test_journal_fixture_matches_fresh_synth():
    pinned = CAL.load_journal(JOURNAL_FIXTURE)
    assert pinned == CAL.synthesize_journal(), (
        "tests/fixtures/dintcal_journal.jsonl drifted from "
        f"synthesize_journal() — {REGEN}")


def test_pinned_calib_reproduced_bit_for_bit_from_evidence_fixture():
    """THE fit acceptance pin: refitting the checked-in evidence
    reproduces the pinned CALIB.json exactly — coefficients, wave
    table, provenance hashes, every field."""
    ev = CAL.load_evidence(EVIDENCE_FIXTURE)
    refit = CAL.fit_calib(ev, source="tests/fixtures/dintcal_evidence.json")
    with open(CALIB_PINNED) as fh:
        pinned = json.load(fh)
    assert refit == pinned, (
        "CALIB.json drifted from the evidence fixture — re-pin: "
        "python tools/dintcal.py fit tests/fixtures/dintcal_evidence.json"
        " -o CALIB.json")
    # and the provenance discipline holds on its face
    assert pinned["provenance"]["calib_hash"] == CAL.calib_hash(pinned)
    assert pinned["provenance"]["evidence_hash"] == CAL._digest(ev)


def test_journal_fixture_audits_clean():
    assert CAL.audit_journal(CAL.load_journal(JOURNAL_FIXTURE)) == []


def test_journal_jsonl_roundtrip(tmp_path):
    doc = CAL.synthesize_journal()
    p = tmp_path / "j.jsonl"
    CAL.dump_journal_jsonl(doc, p)
    assert CAL.load_journal(p) == doc
    # header carries the schema + the cfg the auditor replays under
    head = json.loads(p.read_text().splitlines()[0])
    assert head["kind"] == "dintcal_journal"
    assert head["schema"] == 1
    assert tuple(head["cfg"]["widths"]) == ControllerCfg().widths


# ------------------------------------------------------ evidence gather


def test_gather_evidence_deep_walks_artifact_shapes():
    """bench/exp artifacts are nested dicts/lists: controller snapshots
    (service_samples), dintscope breakdown blocks, and serve counter
    dicts are all folded in wherever they appear."""
    snap = {"service_samples": {"n": 3, "samples": [[16, 150.7],
                                                   [64, 152.6]]}}
    art = {
        "metric": "x", "extra": [
            {"controller": snap},
            {"kind": "dintscope_breakdown",
             "waves": {"dint.tatp_dense.lock":
                       {"ms_per_step": 0.01, "bytes_per_step": 1536,
                        "gbps": 0.15},
                       "dint.tatp_dense.arb": {"ms_per_step": 0.02}}},
        ],
        "counters": {"serve_shed_lanes": 7, "other": 1},
    }
    ev = CAL.gather_evidence([art, {"counters": {"serve_shed_lanes": 2}}],
                             sources=["a.json", "b.json"])
    assert ev["samples"] == [[16, 150.7], [64, 152.6]]
    assert ev["waves"]["dint.tatp_dense.lock"]["bytes_per_step"] == 1536
    assert "dint.tatp_dense.arb" in ev["waves"]   # compute-only kept
    assert ev["counters"] == {"serve_shed_lanes": 9}
    assert ev["sources"] == ["a.json", "b.json"]
    # gathering is purely structural: same input, same hash
    assert CAL._digest(ev) == CAL._digest(
        CAL.gather_evidence([art, {"counters": {"serve_shed_lanes": 2}}],
                            sources=["a.json", "b.json"]))


# -------------------------------------------------- tolerance-band check


def test_check_calib_clean_then_names_drift():
    calib = CAL.load_calib(CALIB_PINNED)
    ev = CAL.load_evidence(EVIDENCE_FIXTURE)
    assert CAL.check_calib(calib, ev) == []

    bad = copy.deepcopy(ev)
    bad["samples"] = [[w, us * 1.2] for w, us in bad["samples"]]
    drifts = CAL.check_calib(calib, bad)
    assert {d["name"] for d in drifts} == {"base_us", "per_lane_ns"}
    assert all(d["what"] == "coefficient" for d in drifts)

    bad = copy.deepcopy(ev)
    wave = "dint.tatp_dense.lock"
    bad["waves"][wave]["ms_per_step"] *= 2       # half the implied GB/s
    drifts = CAL.check_calib(calib, bad)
    assert [d["name"] for d in drifts] == [wave]
    assert wave in drifts[0]["message"]

    # within-band noise does NOT drift (tolerance is the contract)
    ok = copy.deepcopy(ev)
    ok["samples"] = [[w, us * 1.01] for w, us in ok["samples"]]
    assert CAL.check_calib(calib, ok) == []


# ------------------------------------------------------------ the audit


def test_audit_names_tampered_decisions():
    doc = CAL.synthesize_journal()
    kinds = [e["kind"] for e in doc["entries"]]
    iw, ish = kinds.index("width"), kinds.index("shed")
    ihf = kinds.index("hot_frac")

    t = copy.deepcopy(doc)
    t["entries"][iw]["decision"]["width"] = 99999
    v = CAL.audit_journal(t)
    assert len(v) == 1 and v[0]["index"] == iw
    assert f"block {doc['entries'][iw]['block']}" in v[0]["message"]

    t = copy.deepcopy(doc)
    t["entries"][ish]["decision"]["shed"] += 1
    v = CAL.audit_journal(t)
    assert len(v) == 1 and v[0]["index"] == ish and v[0]["kind"] == "shed"

    t = copy.deepcopy(doc)
    t["entries"][ihf]["decision"]["hot_frac"] = 0.5
    v = CAL.audit_journal(t)
    assert len(v) == 1 and v[0]["kind"] == "hot_frac"

    t = copy.deepcopy(doc)
    t["entries"][iw]["kind"] = "mystery"
    assert "unknown journal entry kind" in \
        CAL.audit_journal(t)[0]["message"]

    with pytest.raises(ValueError, match="dintcal_journal"):
        CAL.audit_journal({"kind": "nope"})
    with pytest.raises(ValueError, match="schema"):
        CAL.audit_journal({"kind": "dintcal_journal", "schema": 99})


# ----------------------------------- the engine journal (the producer)

# geometry shared with tests/test_dintserve.py so every jit here is a
# process-wide cache hit
N_ACC = 400
W = 64
CPB = 2


def _overload_engine(seed=0):
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=seed)
    eng.run(constant_schedule(800_000.0, 0.01))
    eng.close()
    return eng


def test_engine_journal_deterministic_reconciled_and_audits_clean():
    """The tentpole pins in one trajectory: (a) same (schedule, seed)
    under VirtualClock => BYTE-identical journal; (b) the journal's shed
    entries reconcile exactly with the host shed tally AND the dintmon
    serve_shed_lanes counter; (c) every recorded decision replays
    bit-for-bit through the pure policy functions; (d) the journal rides
    the snapshot (and therefore every bench/exp serve artifact)."""
    a, b = _overload_engine(), _overload_engine()
    doc_a, doc_b = a.ctl.journal_doc(), b.ctl.journal_doc()
    assert json.dumps(doc_a, sort_keys=True) == \
        json.dumps(doc_b, sort_keys=True)

    rep = a.snapshot()
    entries = doc_a["entries"]
    assert {e["kind"] for e in entries} >= {"width", "shed"}
    shed_logged = sum(e["decision"]["shed"] for e in entries
                      if e["kind"] == "shed")
    assert shed_logged == rep["shed"] > 0
    assert shed_logged == rep["counters"]["serve_shed_lanes"]

    assert CAL.audit_journal(doc_a) == []
    # the recorded width decisions ARE the switch trajectory: every
    # switch block appears as a journaled width entry changing width
    switched = [(e["block"], e["decision"]["width"]) for e in entries
                if e["kind"] == "width" and e["switched"]]
    assert switched == [tuple(s) for s in rep["controller"]["switches"]]

    # (d) the journal + the fit-feeding samples ride the snapshot
    assert rep["controller"]["journal"] == entries
    ss = rep["controller"]["service_samples"]
    assert ss["n"] >= len(ss["samples"]) > 0

    # journal meta pins the exact policy the auditor replays under
    assert doc_a["schema"] == 1
    assert doc_a["model"] == {"base_us": a.model.base_us,
                              "per_lane_ns": a.model.per_lane_ns}


def test_controller_journal_matches_policy_reevaluations():
    """Width entries land exactly on the policy re-evaluations (block 0,
    then every block once the hysteresis window has elapsed), replay
    clean, and the fit-sample buffer keeps the FIRST SAMPLE_CAP
    observations while counting all of them."""
    cfg = ControllerCfg()
    ctl = WidthController(cfg, ServiceModel())
    for _ in range(3 * cfg.hysteresis_blocks):
        w = ctl.width()
        ctl.observe_rate(1000.0)
        ctl.observe_service(w, 160.0)
    n_width = sum(e["kind"] == "width" for e in ctl.journal)
    assert n_width == 1 + 2 * cfg.hysteresis_blocks
    assert CAL.audit_journal(ctl.journal_doc()) == []
    ctl2 = WidthController(cfg, ServiceModel())
    for i in range(600):
        ctl2.observe_service(256, 160.0 + i)
    assert ctl2.samples_seen == 600
    assert len(ctl2.samples) == 512     # SAMPLE_CAP, keep-first
    assert ctl2.samples[0] == [256, 160.0]


# -------------------------------------------------- the calib_check pass


def _pass_check(calib, plan=None):
    from dint_tpu.analysis.passes import calib_check as CC
    return CC.check_calib_doc(calib, "fixture/calib_check", plan=plan,
                              source_dir=REPO)


def broken_calib_findings():
    """The canonical broken calibration fixture (hand-edited coefficient
    => unfit-model + stale-provenance), also imported by test_dintlint's
    every-pass liveness parametrization. Findings anchor to
    fixture/calib_check."""
    doc = CAL.load_calib(CALIB_PINNED)
    doc["model"]["base_us"] += 1.0      # the hand edit the gate exists for
    return _pass_check(doc)


def test_calib_check_clean_on_pinned_artifacts():
    from dint_tpu.analysis import plan as P
    calib = CAL.load_calib(CALIB_PINNED)
    assert _pass_check(calib, plan=P.load_plan()) == []


def test_calib_check_broken_fixture_fires():
    codes = {f.code for f in broken_calib_findings()}
    assert codes == {"unfit-model", "stale-provenance"}


@pytest.mark.parametrize("mutate,code", [
    (lambda d: d.pop("fit"), "malformed-calib"),
    (lambda d: d["model"].__setitem__("per_lane_ns", float("nan")),
     "malformed-calib"),
    (lambda d: d["provenance"].__setitem__("calib_hash", "0" * 16),
     "stale-provenance"),
    (lambda d: d["samples"].__setitem__(0, [d["samples"][0][0],
                                            d["samples"][0][1] + 5.0]),
     "unfit-model"),
    (lambda d: d["waves"].__setitem__("dint.tatp_dense.nope",
                                     {"ms_per_step": 1.0,
                                      "bytes_per_step": 1.0,
                                      "gbps": 1e-9}),
     "unregistered-wave"),
    (lambda d: d["waves"]["dint.tatp_dense.lock"].__setitem__(
        "gbps", 12345.0), "unregistered-wave"),
])
def test_calib_check_codes_fire(mutate, code):
    doc = CAL.load_calib(CALIB_PINNED)
    mutate(doc)
    if code != "stale-provenance":      # keep the hash consistent so the
        doc["provenance"]["calib_hash"] = CAL.calib_hash(doc)  # code under
    findings = _pass_check(doc)         # test is the one that fires
    assert code in {f.code for f in findings}, \
        [f.code for f in findings]


def test_calib_check_plan_model_attribution():
    """Cross-artifact: the plan's serve rows must have been priced with
    the model the resolver picks now."""
    from dint_tpu.analysis import plan as P
    calib = CAL.load_calib(CALIB_PINNED)
    plan = P.load_plan()

    doctored = copy.deepcopy(plan)
    for e in doctored["workloads"].values():
        if isinstance(e.get("serve"), dict):
            e["serve"]["model"]["hash"] = "f" * 16
    fs = _pass_check(calib, plan=doctored)
    assert {f.code for f in fs} == {"plan-model-drift"}

    doctored = copy.deepcopy(plan)
    for e in doctored["workloads"].values():
        if isinstance(e.get("serve"), dict):
            e["serve"]["model"].update(source="defaults", hash=None)
    fs = _pass_check(calib, plan=doctored)
    assert {f.code for f in fs} == {"plan-model-drift"}
    assert any("DEFAULTS" in f.message for f in fs)

    # plan says calib but no calib readable -> missing-calib
    fs = _pass_check(None, plan=plan)
    assert {f.code for f in fs} == {"missing-calib"}


def test_calib_check_anchoring_and_opt_in(monkeypatch, tmp_path):
    """The registered pass lands whole-artifact findings exactly once
    (the anchor target) and returns [] when calibration is not in use
    (no CALIB.json and no calib-sourced plan rows)."""
    from dint_tpu import analysis
    from dint_tpu.analysis import plan as P
    from dint_tpu.analysis.passes.calib_check import calib_check

    class _T:                           # a trace stub off-anchor
        name = "smallbank_dense/block"
    assert calib_check(_T()) == []

    # opt-out world: no calib anywhere, plan priced with defaults
    monkeypatch.setenv(CAL.ENV_CALIB_PATH, str(tmp_path / "none.json"))
    plain = copy.deepcopy(P.load_plan())
    for e in plain["workloads"].values():
        if isinstance(e.get("serve"), dict):
            e["serve"]["model"].update(source="defaults", hash=None)
    ppath = tmp_path / "plan.json"
    ppath.write_text(json.dumps(plain))
    monkeypatch.setenv(P.ENV_PLAN_PATH, str(ppath))

    class _A:
        name = os.environ.get(P.ENV_PLAN_ANCHOR, P.DEFAULT_ANCHOR)
    assert calib_check(_A()) == []
    assert not analysis.has_errors([])


# ----------------------------------------- the resolver + plan threading


def test_resolve_service_model_prefers_calib_and_says_so(monkeypatch,
                                                         tmp_path):
    model, meta = CAL.resolve_service_model()      # the pinned CALIB.json
    calib = CAL.load_calib(CALIB_PINNED)
    assert meta["source"] == "calib"
    assert meta["hash"] == calib["provenance"]["calib_hash"]
    assert (model.base_us, model.per_lane_ns) == \
        (calib["model"]["base_us"], calib["model"]["per_lane_ns"])

    monkeypatch.setenv(CAL.ENV_CALIB_PATH, str(tmp_path / "absent.json"))
    model, meta = CAL.resolve_service_model()
    assert meta == {"source": "defaults", "path": None, "hash": None}
    assert (model.base_us, model.per_lane_ns) == (150.0, 40.0)

    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    model, meta = CAL.resolve_service_model(bad)   # soft-fail, never raise
    assert meta["source"] == "defaults"


def test_plan_serve_rows_record_model_provenance():
    """ISSUE 18 satellite fix: serve_priors no longer instantiates
    ServiceModel() unconditionally — the pinned plan's serve rows carry
    the resolver's coefficients plus source + hash."""
    from dint_tpu.analysis import plan as P
    calib = CAL.load_calib(CALIB_PINNED)
    plan = P.load_plan()
    rows = [e["serve"] for e in plan["workloads"].values()
            if isinstance(e.get("serve"), dict)]
    assert rows
    for serve in rows:
        m = serve["model"]
        assert m["source"] == "calib"
        assert m["hash"] == calib["provenance"]["calib_hash"]
        assert m["base_us"] == calib["model"]["base_us"]
        assert m["per_lane_ns"] == calib["model"]["per_lane_ns"]
    # and the live function agrees with the pinned artifact
    wl = next(w for w in P.WORKLOADS if w.serve)
    fresh = P.serve_priors(wl)
    assert fresh["model"]["source"] == "calib"
    assert fresh["model"]["hash"] == calib["provenance"]["calib_hash"]


# ----------------------------------------------------------------- CLI


def test_cli_fit_reproduces_pinned_calib(tmp_path, capsys):
    main = _cli_main()
    out = tmp_path / "CALIB.json"
    rc = main(["fit", EVIDENCE_FIXTURE, "-o", str(out),
               "--source", "tests/fixtures/dintcal_evidence.json",
               "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    with open(CALIB_PINNED) as fh:
        pinned = json.load(fh)
    assert rep["model"] == pinned["model"]
    assert rep["provenance"] == pinned["provenance"]
    assert json.loads(out.read_text()) == pinned


def test_cli_audit_exit_codes(tmp_path, capsys):
    main = _cli_main()
    assert main(["audit", JOURNAL_FIXTURE]) == 0
    capsys.readouterr()

    lines = open(JOURNAL_FIXTURE).read().splitlines()
    e = json.loads(lines[1])
    assert e["kind"] == "width"
    e["decision"]["width"] = 99999      # the hand tamper
    lines[1] = json.dumps(e, sort_keys=True)
    bad = tmp_path / "tampered.jsonl"
    bad.write_text("\n".join(lines) + "\n")
    rc = main(["audit", str(bad), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["n_violations"] == 1
    assert f"block {e['block']}" in out["violations"][0]["message"]


def test_cli_synth_regenerates_checked_in_fixtures(tmp_path, capsys):
    """File-level drift guard: `dintcal synth` into a scratch dir
    reproduces the checked-in fixture FILES byte-for-byte."""
    main = _cli_main()
    ev, jn = tmp_path / "e.json", tmp_path / "j.jsonl"
    assert main(["synth", "--out-evidence", str(ev),
                 "--out-journal", str(jn)]) == 0
    capsys.readouterr()
    assert ev.read_text() == open(EVIDENCE_FIXTURE).read(), REGEN
    assert jn.read_text() == open(JOURNAL_FIXTURE).read(), REGEN


def test_cli_propose_emits_repin_recipe(tmp_path, capsys):
    main = _cli_main()
    out = tmp_path / "CALIB.proposed.json"
    rc = main(["propose", "--evidence", EVIDENCE_FIXTURE,
               "-o", str(out), "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["delta"]["base_us"]["pinned"] == \
        rep["delta"]["base_us"]["proposed"]     # clean evidence: no move
    assert "dintplan.py plan --calib" in rep["repin"]
    proposed = json.loads(out.read_text())
    with open(CALIB_PINNED) as fh:
        assert proposed["model"] == json.load(fh)["model"]


def test_cli_check_clean_then_drift_names_offender(tmp_path, capsys):
    """THE check acceptance pin: rc 0 on the clean fixture; rc 1 on
    injected drift, NAMING the wave and the coefficient."""
    main = _cli_main()
    assert main(["check"]) == 0
    capsys.readouterr()

    ev = CAL.load_evidence(EVIDENCE_FIXTURE)
    bad = copy.deepcopy(ev)
    wave = "dint.tatp_dense.install"
    bad["waves"][wave]["ms_per_step"] *= 3
    bad["samples"] = [[w, us * 1.3] for w, us in bad["samples"]]
    bpath = tmp_path / "drifted_evidence.json"
    bpath.write_text(json.dumps(bad))
    rc = main(["check", "--evidence", str(bpath), "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 1 and not rep["ok"]
    sites = {f["site"] for f in rep["findings"]
             if f["code"] == "evidence-drift"}
    assert f"wave:{wave}" in sites
    assert {"coefficient:base_us", "coefficient:per_lane_ns"} <= sites


def test_cli_describe_reports_resolver_source(capsys):
    main = _cli_main()
    assert main(["describe", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["resolved_model"]["source"] == "calib"
    assert rep["calib_schema"] == CAL.CALIB_SCHEMA


# --------------------------------------------- dintserve CLI integration


def _serve_cli(*args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintserve.py"),
         *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO)


def _serve_main():
    """tools/dintserve.py main(), loaded in-process (simulate is pure
    controller math — no engine, no fresh jax import per invocation)."""
    spec = importlib.util.spec_from_file_location(
        "dintserve_cli", os.path.join(REPO, "tools", "dintserve.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def _simulate(main, capsys, monkeypatch, *extra):
    monkeypatch.setattr(sys, "argv",
                        ["dintserve", "simulate", "--rate", "20000000",
                         "--window", "0.004", "--json", *extra])
    assert main() == 0
    return json.loads(capsys.readouterr().out)


def test_dintserve_simulate_reports_model_source(capsys, monkeypatch):
    """Satellite: simulated capacity claims are attributable — the
    simulate report names the ServiceModel source (CALIB.json here;
    explicit flags report source=flags)."""
    main = _serve_main()
    rep = _simulate(main, capsys, monkeypatch)
    calib = CAL.load_calib(CALIB_PINNED)
    assert rep["model"]["source"] == "calib"
    assert rep["model"]["hash"] == calib["provenance"]["calib_hash"]
    assert rep["model"]["base_us"] == calib["model"]["base_us"]
    rep_b = _simulate(main, capsys, monkeypatch, "--model-base-us", "150")
    assert rep_b["model"]["source"] == "flags"
    assert rep_b["model"]["hash"] is None


@pytest.mark.slow
def test_dintserve_run_streams_auditable_journal(tmp_path):
    """Satellite: `dintserve run --journal PATH` streams the decision
    journal as JSONL, and `dintcal audit` replays it clean."""
    jpath = tmp_path / "journal.jsonl"
    c = _serve_cli("run", "--engine", "smallbank_dense", "--size",
                   str(N_ACC), "--rate", "800000", "--window", "0.01",
                   "--widths", f"16,{W}", "--cpb", str(CPB), "--virtual",
                   "--no-gate", "--json", "--journal", str(jpath))
    assert c.returncode == 0, c.stderr
    doc = CAL.load_journal(jpath)
    assert doc["entries"]
    assert CAL.audit_journal(doc) == []
    rep = json.loads(c.stdout.strip().splitlines()[-1])
    assert rep["controller"]["journal"] == doc["entries"]
