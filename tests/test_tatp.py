import jax
import numpy as np

from dint_tpu.clients import tatp_client as tc
from dint_tpu.engines import tatp
from dint_tpu.engines.types import Op, Reply, make_batch

VW = 4
P = 200  # subscribers


def _shards(rng):
    return tc.populate_shards(rng, P, val_words=VW,
                              cf_buckets=1 << 10, cf_lock_slots=1 << 10,
                              log_capacity=1 << 14)


def _b(ops, tbls, keys, vals=None, vers=None, width=64):
    return make_batch(ops, np.asarray(keys, np.uint64), vals, vers=vers,
                      tables=np.asarray(tbls, np.int32), width=width, val_words=VW)


def test_dense_occ_read_lock_commit(rng):
    shards, _ = _shards(rng)
    s = shards[0]
    step = jax.jit(tatp.step)
    # read sub 5, lock it, second lock rejected
    b = _b([Op.OCC_READ, Op.OCC_LOCK, Op.OCC_LOCK],
           [tatp.SUBSCRIBER] * 3, [5, 5, 5])
    s, rep = step(s, b)
    rt = np.asarray(rep.rtype)
    assert list(rt[:3]) == [Reply.VAL, Reply.GRANT, Reply.REJECT]
    v1 = np.asarray(rep.ver)[0]
    # commit installs + unlocks; re-read sees new val, ver+1; lock regrantable
    nv = np.zeros((1, VW), np.uint32)
    nv[0, 0] = 777
    nv[0, 1] = tc.MAGIC
    s, rep = step(s, _b([Op.COMMIT_PRIM], [tatp.SUBSCRIBER], [5], nv))
    s, rep = step(s, _b([Op.OCC_READ, Op.OCC_LOCK], [tatp.SUBSCRIBER] * 2, [5, 5]))
    assert np.asarray(rep.rtype)[0] == Reply.VAL
    assert np.asarray(rep.val)[0, 0] == 777
    assert np.asarray(rep.ver)[0] == v1 + 1
    assert np.asarray(rep.rtype)[1] == Reply.GRANT


def test_cf_insert_delete_cycle(rng):
    shards, cf_keys = _shards(rng)
    s = shards[0]
    step = jax.jit(tatp.step)
    # pick a cf key that does NOT exist
    k = 0
    while k in set(int(x) for x in cf_keys):
        k += 1
    b = _b([Op.OCC_READ], [tatp.CALL_FORWARDING], [k])
    s, rep = step(s, b)
    assert np.asarray(rep.rtype)[0] == Reply.NOT_EXIST
    # lock + insert prim
    s, rep = step(s, _b([Op.OCC_LOCK], [tatp.CALL_FORWARDING], [k]))
    assert np.asarray(rep.rtype)[0] == Reply.GRANT
    nv = np.zeros((1, VW), np.uint32)
    nv[0, 0] = 42
    nv[0, 1] = tc.MAGIC
    s, rep = step(s, _b([Op.INSERT_PRIM], [tatp.CALL_FORWARDING], [k], nv))
    assert np.asarray(rep.rtype)[0] == Reply.ACK
    # lock released by INSERT_PRIM; read finds it
    s, rep = step(s, _b([Op.OCC_LOCK, Op.OCC_READ],
                        [tatp.CALL_FORWARDING] * 2, [k, k]))
    assert np.asarray(rep.rtype)[0] == Reply.GRANT
    assert np.asarray(rep.rtype)[1] == Reply.VAL
    assert np.asarray(rep.val)[1, 0] == 42
    # delete + verify gone
    s, rep = step(s, _b([Op.DELETE_PRIM], [tatp.CALL_FORWARDING], [k]))
    assert np.asarray(rep.rtype)[0] == Reply.ACK
    s, rep = step(s, _b([Op.OCC_READ], [tatp.CALL_FORWARDING], [k]))
    assert np.asarray(rep.rtype)[0] == Reply.NOT_EXIST


def test_end_to_end_cohorts(rng):
    shards, _ = _shards(rng)
    coord = tc.Coordinator(shards, P, width=2048, val_words=VW)
    for _ in range(4):
        coord.run_cohort(rng, 256)
    st = coord.stats
    assert st.attempted == 4 * 256
    assert st.committed > st.attempted * 0.5
    accounted = st.committed + st.aborted_lock + st.aborted_validate + st.aborted_missing
    assert accounted == st.attempted

    # all locks free at the end
    for s in coord.shards:
        assert not np.asarray(s.sub_lock).any()
        assert not np.asarray(s.sf_lock).any()
        assert not np.asarray(s.cf_lock.locked).any()

    # replicas converged on every table
    s0 = coord.shards[0]
    for s in coord.shards[1:]:
        for tb in ("sub", "sec", "ai", "sf"):
            assert np.array_equal(np.asarray(getattr(s0, tb).val),
                                  np.asarray(getattr(s, tb).val))
            assert np.array_equal(np.asarray(getattr(s0, tb).ver),
                                  np.asarray(getattr(s, tb).ver))
        from dint_tpu.tables import kv as kvt
        assert kvt.to_dict(s0.cf) == kvt.to_dict(s.cf)

    # log heads advanced identically on all shards
    heads = [int(np.asarray(s.log.head).sum()) for s in coord.shards]
    assert heads[0] == heads[1] == heads[2]
