"""Device-fused TATP pipeline: invariants + parity with the host coordinator.

The fused pipeline (engines/tatp_pipeline.py) must preserve the host
coordinator's semantics (clients/tatp_client.py): disjoint abort accounting,
magic-byte integrity on every read, and — the replication contract — the 3
replicas' table contents staying bit-identical after every cohort
(SURVEY.md §2.3: every record on all 3 servers)."""
import jax
import numpy as np
import pytest

from dint_tpu.clients import tatp_client as tc
from dint_tpu.engines import tatp, tatp_pipeline as tp


@pytest.fixture(scope="module")
def _stacked0():
    rng = np.random.default_rng(7)
    shards, _ = tc.populate_shards(rng, 64, val_words=4, cf_buckets=1 << 10,
                                   cf_lock_slots=1 << 10,
                                   log_capacity=1 << 14)
    return tp.stack_shards(shards)


@pytest.fixture
def stacked(_stacked0):
    # runners donate their state argument; hand each test its own buffers
    return jax.tree.map(jax.numpy.array, _stacked0)


def _dense_replicas_equal(st: tatp.Shard):
    for t in (st.sub, st.sec, st.ai, st.sf):
        for arr in (t.val, t.ver):
            a = np.asarray(arr)
            assert (a[0] == a[1]).all() and (a[0] == a[2]).all()
    for arr in (st.cf.key_hi, st.cf.key_lo, st.cf.ver, st.cf.valid):
        a = np.asarray(arr)
        assert (a[0] == a[1]).all() and (a[0] == a[2]).all()


def test_cohorts_run_and_account(stacked):
    run = tp.build_runner(64, w=128, val_words=4, cohorts_per_block=3)
    key = jax.random.PRNGKey(0)
    st = stacked
    total = np.zeros(tp.N_STATS, np.int64)
    for i in range(3):
        st, stats = run(st, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)

    attempted = total[tp.STAT_ATTEMPTED]
    assert attempted == 3 * 3 * 128
    # disjoint accounting: every attempt is exactly one of these
    assert (total[tp.STAT_COMMITTED] + total[tp.STAT_AB_LOCK]
            + total[tp.STAT_AB_MISSING] + total[tp.STAT_AB_VALIDATE]
            == attempted)
    assert total[tp.STAT_MAGIC_BAD] == 0
    assert total[tp.STAT_COMMITTED] > 0.5 * attempted
    # replication contract: replicas stay bit-identical
    _dense_replicas_equal(st)


def test_no_locks_leak(stacked):
    """After full cohorts (commits release at owner, aborts unlock), no row
    lock may stay held between cohorts on any replica."""
    run = tp.build_runner(64, w=128, val_words=4, cohorts_per_block=4)
    st, _ = run(stacked, jax.random.PRNGKey(3))
    for lock in (st.sub_lock, st.sec_lock, st.ai_lock, st.sf_lock):
        assert not np.asarray(lock).any()
    assert not np.asarray(st.cf_lock.locked).any()


@pytest.mark.slow  # ~23s; accounting + lock-leak checks stay tier-1
def test_abort_rate_matches_host_coordinator():
    """Same workload params -> fused and host-wave abort rates agree within
    noise (both serialize conflicts by per-cohort lock certification)."""
    n_sub, w, iters = 48, 256, 6
    rng = np.random.default_rng(11)
    shards, _ = tc.populate_shards(rng, n_sub, val_words=4,
                                   cf_buckets=1 << 10, cf_lock_slots=1 << 10,
                                   log_capacity=1 << 14)
    coord = tc.Coordinator(shards, n_sub, width=2048, val_words=4)
    for _ in range(iters):
        coord.run_cohort(rng, w)

    shards2, _ = tc.populate_shards(np.random.default_rng(11), n_sub,
                                    val_words=4, cf_buckets=1 << 10,
                                    cf_lock_slots=1 << 10,
                                    log_capacity=1 << 14)
    run = tp.build_runner(n_sub, w=w, val_words=4, cohorts_per_block=iters)
    _, stats = run(tp.stack_shards(shards2), jax.random.PRNGKey(5))
    tot = np.asarray(stats, np.int64).sum(axis=0)
    fused_rate = 1 - tot[tp.STAT_COMMITTED] / tot[tp.STAT_ATTEMPTED]
    assert abs(fused_rate - coord.stats.abort_rate) < 0.08
