"""dintmut: the mutation-coverage plane proven on the pinned artifact.

Covers the acceptance contract of the mutation gate:
  * the operator registry and the quick sample are deterministic
    (hashes and draws reproduce bit-for-bit),
  * mutant discovery on a live trace reproduces the pinned cell ids and
    every discovered mutant builds a walkable ClosedJaxpr,
  * the pinned MUTCOV.json attributes >= 1 kill to every operator's
    expected pass family and to every required gate family,
  * survivor triage = an allowlist entry pinned to the CELL ID; a
    mis-scoped entry suppresses nothing,
  * every drift class (edited cells, edited summary, forged quick
    sample, missing/mis-schemaed artifact) fails closed with a
    regeneration hint,
  * the ring-family cells stay cross-referenced against the ONE standing
    durability/no-ring-truncation allowlist entry,
  * the CLI round-trips (report/check/describe, --json payloads, exit
    discipline) — in-process, sharing the TraceCache.

The full-matrix re-execution (every mutant re-run, bit-for-bit against
the pinned rows) is the slow tier; tier-1 re-executes one pinned
quick-sample cell on the anchor target.
"""
import copy
import json
import os

import pytest

from dint_tpu import analysis
from dint_tpu.analysis import allowlist as al
from dint_tpu.analysis import mutate as M
from dint_tpu.analysis import targets as T
from dint_tpu.analysis.passes import mut_check as MC

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MUTCOV_PINNED = os.path.join(REPO, "MUTCOV.json")
ANCHOR = "tatp_dense/block"

pytestmark = pytest.mark.mut

_DOC = None


def _doc() -> dict:
    """A fresh deep copy of the pinned MUTCOV.json (loaded once)."""
    global _DOC
    if _DOC is None:
        _DOC = M.load_mutcov(MUTCOV_PINNED)
    return copy.deepcopy(_DOC)


def _repin(doc: dict) -> dict:
    """Re-derive summary/quick/provenance after a cell edit, so ONLY the
    policy checks see the edit (provenance/summary checks stay green)."""
    doc["summary"] = M._summary(doc["cells"])
    doc["quick"] = {"seed": M.QUICK_SEED,
                    "cells": M.quick_sample(doc["cells"], M.QUICK_SEED)}
    doc["provenance"] = {"registry": M.registry_hash(),
                         "matrix": M.matrix_hash(),
                         "cells": M._digest(doc["cells"])}
    return doc


def codes(findings):
    return {f.code for f in findings}


# ------------------------------------------------------------ determinism


def test_registry_and_matrix_hashes_are_deterministic():
    assert M.registry_hash() == M.registry_hash()
    assert M.matrix_hash() == M.matrix_hash()
    # the digest is order-insensitive over dict keys (sort_keys pinned)
    assert M._digest({"a": 1, "b": 2}) == M._digest({"b": 2, "a": 1})
    assert M._digest([1, 2]) != M._digest([2, 1])


def test_quick_sample_is_deterministic_and_pinned():
    doc = _doc()
    cells = doc["cells"]
    seed = doc["quick"]["seed"]
    draw1 = M.quick_sample(cells, seed)
    draw2 = M.quick_sample(cells, seed)
    assert draw1 == draw2 == doc["quick"]["cells"]
    # one representative per operator, all real cell ids
    ids = {c["id"] for c in cells}
    assert set(draw1) <= ids
    assert len({i.split("|")[1] for i in draw1}) == len(draw1)


def test_discovery_reproduces_the_pinned_anchor_cells():
    """Mutant discovery on a live trace is deterministic and matches the
    pinned matrix: same cell ids, same sites, same notes."""
    trace = T.get_trace(ANCHOR)
    ops = _doc()["targets"][ANCHOR]["operators"]
    muts1 = M.discover(trace, ops)
    muts2 = M.discover(trace, ops)
    assert [m.cell_id for m in muts1] == [m.cell_id for m in muts2]
    assert [(m.site, m.note) for m in muts1] \
        == [(m.site, m.note) for m in muts2]
    pinned = [(c["id"], c["site"], c["note"]) for c in _doc()["cells"]
              if c["target"] == ANCHOR]
    assert [(m.cell_id, m.site, m.note) for m in muts1] == pinned


def test_every_discovered_mutant_builds():
    """Each mutant rewrite produces a ClosedJaxpr the passes can walk —
    the corruption is structural, never a crash of the mutator itself."""
    import jax._src.core as jcore
    trace = T.get_trace(ANCHOR)
    muts = M.discover(trace, _doc()["targets"][ANCHOR]["operators"])
    assert muts, "anchor target produced no mutants"
    for m in muts:
        mutated = m.build(trace.closed_jaxpr)
        assert isinstance(mutated, jcore.ClosedJaxpr)
        # the rewrite returned a NEW object; the cached trace is intact
        assert mutated is not trace.closed_jaxpr


# ------------------------------------------------- pinned-evidence policy


def test_pinned_matrix_clears_the_policy_bar():
    """The committed MUTCOV.json is itself gate-clean: kill rate over
    floor, no dormant operator, every required family attributed."""
    doc = _doc()
    s = doc["summary"]
    assert s["kill_rate"] >= doc["kill_rate_floor"]
    assert s["n_cells"] == len(doc["cells"])
    fs = MC.check_mutcov(doc, ANCHOR)
    # survivors are the only permitted errors, and each one is triaged
    # by a site-pinned entry in the shared repo allowlist
    assert codes(fs) <= {"survivor"}
    entries = al.load(os.path.join(REPO, "tools", "dintlint_allow.json"))
    fs = al.apply(fs, entries, check_unused=False)
    assert not analysis.has_errors(fs)


def test_every_operator_kills_within_its_expected_family():
    """>= 1 kill per operator, attributed to a pass that operator's
    registry entry declares it expects — the per-operator kill proof."""
    doc = _doc()
    by_op: dict[str, list[dict]] = {}
    for c in doc["cells"]:
        by_op.setdefault(c["operator"], []).append(c)
    assert set(by_op) == set(M.OPERATORS), "matrix lost an operator"
    for name, cells in by_op.items():
        killed = [c for c in cells if c["verdict"] == "killed"]
        assert killed, f"operator {name} killed nothing"
        expect = {e.split("/", 1)[0] for e in M.OPERATORS[name].expect}
        for c in killed:
            kpass = c["killer"].split("/", 1)[0]
            assert kpass in expect, \
                f"{c['id']}: killer {c['killer']} outside {expect}"


def test_required_families_each_attribute_a_kill():
    killers = set(_doc()["summary"]["killer_passes"])
    assert "protocol" in killers
    assert "durability" in killers
    assert "cost_budget" in killers
    assert killers & MC._CORE_PASSES, "no core dintlint pass kills"


# ------------------------------------------------------- survivor triage


def test_survivor_triage_is_pinned_to_the_cell_id(tmp_path):
    """A survivor is one ERROR whose site is the cell id; only an
    allowlist entry pinned to that exact cell suppresses it."""
    doc = _repin(_doc())
    survivors = [c for c in doc["cells"] if c["verdict"] == "survived"]
    assert survivors, "pinned matrix lost its documented survivors"
    cid = survivors[0]["id"]
    fs = MC.check_mutcov(doc, ANCHOR)
    mine = [f for f in fs if f.code == "survivor" and f.site == cid]
    assert len(mine) == 1

    scoped = [{"pass": "mut_check", "code": "survivor", "site": cid,
               "reason": "documented non-goal (test)"}]
    fs = al.apply(MC.check_mutcov(doc, ANCHOR), scoped,
                  check_unused=False)
    assert not any(f.site == cid and not f.suppressed for f in fs
                   if f.code == "survivor")

    elsewhere = [{"pass": "mut_check", "code": "survivor",
                  "site": "some/other|cell|9", "reason": "mis-scoped"}]
    fs = al.apply(MC.check_mutcov(doc, ANCHOR), elsewhere,
                  check_unused=False)
    assert any(f.site == cid and not f.suppressed for f in fs)


def test_untriaged_survivor_fails_the_gate():
    """Flipping a killed cell to survived (and re-pinning hashes so only
    policy sees it) leaves an unsuppressed survivor ERROR."""
    doc = _doc()
    victim = next(c for c in doc["cells"] if c["verdict"] == "killed")
    victim["verdict"], victim["killer"] = "survived", None
    victim["new_errors"] = []
    _repin(doc)
    fs = MC.check_mutcov(doc, ANCHOR)
    assert any(f.code == "survivor" and f.site == victim["id"]
               for f in fs)
    entries = al.load(os.path.join(REPO, "tools", "dintlint_allow.json"))
    fs = al.apply(fs, entries, check_unused=False)
    assert analysis.has_errors(fs)   # the repo triage does not cover it


# ------------------------------------------------------------ drift guard


def test_edited_cells_trip_stale_provenance_with_regen_hint():
    doc = _doc()
    doc["cells"][0]["verdict"] = "survived"
    fs = MC.check_mutcov(doc, ANCHOR)
    stale = [f for f in fs if f.code == "stale-provenance"]
    assert any(f.site == "cells" for f in stale)
    assert all("dintmut.py run" in f.suggestion for f in stale)


def test_edited_summary_trips_summary_drift():
    doc = _doc()
    doc["summary"]["kill_rate"] = 1.0
    doc["summary"]["n_survived"] = 0
    fs = MC.check_mutcov(doc, ANCHOR)
    assert any(f.code == "summary-drift" and f.site == "summary"
               for f in fs)


def test_forged_quick_sample_trips_summary_drift():
    doc = _doc()
    doc["quick"]["cells"] = doc["quick"]["cells"][:-1]
    fs = MC.check_mutcov(doc, ANCHOR)
    assert any(f.code == "summary-drift" and f.site == "quick"
               for f in fs)


def test_kill_rate_floor_and_dormant_operator_fire():
    doc = _doc()
    for c in doc["cells"]:
        if c["operator"] == "drop-eqn":
            c["verdict"], c["killer"] = "survived", None
            c["new_errors"] = []
    _repin(doc)
    fs = MC.check_mutcov(doc, ANCHOR)
    assert "kill-rate-floor" in codes(fs)        # 10/34 flipped

    doc = _doc()
    doc["cells"] = [c for c in doc["cells"]
                    if c["operator"] != "drop-donation"]
    _repin(doc)
    fs = MC.check_mutcov(doc, ANCHOR)
    assert any(f.code == "operator-dormant" and f.site == "drop-donation"
               for f in fs)


def test_attribution_gap_fires_when_a_family_stops_killing():
    doc = _doc()
    for c in doc["cells"]:
        if c["killer"] and c["killer"].startswith("cost_budget/"):
            c["killer"] = "protocol/unlocked-install"
    _repin(doc)
    fs = MC.check_mutcov(doc, ANCHOR)
    assert any(f.code == "attribution-gap" and f.site == "cost_budget"
               for f in fs)


def test_missing_and_mis_schemaed_artifacts_fail_closed(tmp_path):
    doc, fs = MC.load_mutcov_findings(ANCHOR, str(tmp_path / "no.json"))
    assert doc is None and codes(fs) == {"missing-mutcov"}
    assert "dintmut.py run" in fs[0].suggestion

    bad = tmp_path / "old.json"
    old = _doc()
    old["schema"] = M.SCHEMA + 1
    bad.write_text(json.dumps(old))
    with pytest.raises(ValueError, match="dintmut.py run"):
        M.load_mutcov(str(bad))
    doc, fs = MC.load_mutcov_findings(ANCHOR, str(bad))
    assert doc is None and codes(fs) == {"malformed-mutcov"}


def test_structure_findings_short_circuit():
    doc = _doc()
    del doc["summary"]
    del doc["cells"][0]["killer"]
    fs = MC.check_mutcov(doc, ANCHOR)
    assert codes(fs) == {"malformed-mutcov"}     # nothing else piles on


# ------------------------------------------------------------ ring hygiene


def test_ring_cells_cite_the_standing_truncation_entry(tmp_path):
    doc = _doc()
    ring = [c for c in doc["cells"] if c["operator"] == "ring-shrink"]
    assert ring, "matrix lost its ring-shrink cells"
    for c in ring:
        assert MC._RING_ENTRY in c["suppressed"]

    # a ring cell that stops recording the suppression = drift
    ring[0]["suppressed"] = [s for s in ring[0]["suppressed"]
                             if s != MC._RING_ENTRY]
    _repin(doc)
    fs = MC.check_mutcov(doc, ANCHOR)
    assert any(f.code == "ring-triage-drift" and f.site == ring[0]["id"]
               for f in fs)

    # the standing entry vanishing from the allowlist = drift too
    bare = tmp_path / "allow.json"
    bare.write_text(json.dumps([]))
    fs = MC.check_mutcov(_doc(), ANCHOR, allow_path=str(bare))
    assert any(f.code == "ring-triage-drift"
               and f.site == MC._RING_ENTRY for f in fs)


# --------------------------------------------------- re-execution tiers


def test_quick_cell_reexecutes_bit_for_bit():
    """Tier-1 re-execution: the anchor's pinned quick-sample cell re-runs
    and reproduces its pinned row exactly (the dintgate --quick tier runs
    the whole sample; one target keeps this inside the tier-1 budget)."""
    doc = _doc()
    ids = [i for i in doc["quick"]["cells"]
           if i.split("|")[0] == ANCHOR]
    assert ids, "quick sample no longer covers the anchor"
    fresh = M.run_cells(ids)
    pinned = {c["id"]: c for c in doc["cells"]}
    for cell in fresh:
        want = pinned[cell["id"]]
        for k in ("verdict", "killer", "site", "note", "new_errors",
                  "suppressed"):
            assert cell[k] == want[k], (cell["id"], k)


@pytest.mark.slow
def test_full_matrix_reproduces_pinned_rows():
    """The slow tier: every mutant re-executes and the whole document
    (cells, summary, quick draw, provenance) reproduces bit-for-bit."""
    fresh = M.run_matrix()
    pinned = _doc()
    assert fresh["cells"] == pinned["cells"]
    assert fresh["summary"] == pinned["summary"]
    assert fresh["quick"] == pinned["quick"]
    assert fresh["provenance"] == pinned["provenance"]


# ------------------------------------------------------------------- CLI


def _dintmut_main():
    """Load tools/dintmut.py as a module so main() runs in-process and
    shares this process's TraceCache (no subprocess re-tracing)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dintmut_cli", os.path.join(REPO, "tools", "dintmut.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_cli_report_round_trip(capsys):
    main = _dintmut_main()
    assert main(["report", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["metric"] == "mutation-coverage"
    assert payload["mode"] == "report" and payload["ok"] is True
    assert payload["summary"] == _doc()["summary"]
    assert payload["quick"] == _doc()["quick"]

    assert main(["report"]) == 0
    out = capsys.readouterr().out
    assert "killed" in out and "quick sample" in out
    for cid in (c["id"] for c in _doc()["cells"]
                if c["verdict"] == "survived"):
        assert cid in out                       # survivors always shown


def test_cli_describe_lists_every_operator(capsys):
    main = _dintmut_main()
    assert main(["describe"]) == 0
    out = capsys.readouterr().out
    for name in M.OPERATORS:
        assert name in out
    assert "survivor" in out and "kill-rate-floor" in out


def test_cli_check_quick_passes_on_pinned_artifact(capsys):
    """`dintmut check --quick` (the dintgate tier): static policy gate +
    the pinned deterministic sample re-executed, exit 0 on this tree."""
    main = _dintmut_main()
    assert main(["check", "--quick", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["metric"] == "mutation-coverage"
    assert payload["mode"] == "quick" and payload["ok"] is True
    for k in ("schema", "targets", "allowlist", "n_findings", "n_errors",
              "n_suppressed", "stale_allowlist", "mutcov", "findings"):
        assert k in payload
    # the two documented survivors ride through as SUPPRESSED findings
    assert payload["n_errors"] == 0
    assert payload["n_suppressed"] >= 2


def test_cli_check_fails_on_stale_artifact(tmp_path, capsys, monkeypatch):
    doc = _doc()
    doc["cells"][0]["verdict"] = "survived"
    path = tmp_path / "MUTCOV.json"
    path.write_text(json.dumps(doc))
    monkeypatch.setenv(M.ENV_MUTCOV, str(path))
    main = _dintmut_main()
    assert main(["check", "--quick"]) == 1
    out = capsys.readouterr().out
    assert "stale-provenance" in out


def test_cli_report_missing_artifact_exits_2(tmp_path, capsys,
                                             monkeypatch):
    monkeypatch.setenv(M.ENV_MUTCOV, str(tmp_path / "nope.json"))
    main = _dintmut_main()
    assert main(["report"]) == 2
    assert "dintmut:" in capsys.readouterr().err


# ------------------------------------------------------- lint integration


def broken_mutcov_findings():
    """The canonical broken mutation fixture (a killed cell hand-flipped
    to survived => stale-provenance + survivor), also imported by
    test_dintlint's every-pass liveness parametrization. Findings anchor
    to fixture/mut_check."""
    doc = _doc()
    victim = next(c for c in doc["cells"] if c["verdict"] == "killed")
    victim["verdict"], victim["killer"] = "survived", None
    return MC.check_mutcov(doc, "fixture/mut_check")


def test_mut_check_broken_fixture_fires():
    fs = broken_mutcov_findings()
    assert "stale-provenance" in codes(fs)
    assert "survivor" in codes(fs)


def test_mut_check_anchors_to_one_target(monkeypatch):
    """The pass lands its whole-artifact findings exactly once: on the
    anchor target, [] everywhere else."""
    from dint_tpu.analysis.core import TargetTrace
    off = TargetTrace("smallbank_dense/block", None)
    assert MC.mut_check(off) == []
    monkeypatch.setenv(MC.ENV_MUT_ANCHOR, "smallbank_dense/block")
    assert MC._anchor() == "smallbank_dense/block"
