"""dintcache (round 10): VMEM-resident hot-set serving for the skewed
random-access hot path.

The acceptance bar of ISSUE 5: `DINT_USE_HOTSET=1` must be BIT-IDENTICAL
to the default path on every integrated engine — the hot mirror is a pure
acceleration structure (write-through keeps mirror == table prefix an
invariant), so stats, tables, arb stamps, and log rings cannot move. These
tests pin (a) each hot kernel against its XLA partition AND the plain
round-6 path, including an adversarial batch with duplicate indices
straddling the hot_n boundary; (b) the write-through coherence invariant;
(c) SmallBank dense + sharded, the store engine (Zipfian micro), the
cached store, and skewed-TATP end-to-end bit-identical under the hot tier
on BOTH serving routes (XLA partition and pallas VMEM kernels); (d) the
env/resolve plumbing and the per-kernel probe cache (the round-10 probe
recompile fix); (e) the degrade contract — a broken hot kernel costs the
VMEM residency, never the partition or the measurement."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dint_tpu.clients import workloads as wl
from dint_tpu.engines import smallbank_dense as sd, tatp_dense as td
from dint_tpu.ops import pallas_gather as pg

U32 = jnp.uint32
I32 = jnp.int32


# -------------------------------------------------------- hot kernels


@pytest.mark.parametrize("n,hot,vw,k", [
    (1000, 40, 10, 333),     # val-style wide rows, 4% hot
    (512, 300, 1, 700),      # single words, most of the table mirrored
    (37, 5, 4, 5),           # K below the DMA ring depth
    (64, 1, 2, 64),          # single-row mirror
])
def test_gather_rows_hot_matches_plain_and_xla(rng, n, hot, vw, k):
    tab = jnp.asarray(rng.integers(0, 1 << 32, n * vw, np.int64)
                      .astype(np.uint32))
    mirror = tab[:hot * vw]
    idx = jnp.asarray(rng.integers(0, n, k).astype(np.int32))
    midx = jnp.where(idx < hot, idx, -1)
    got = pg.gather_rows_hot(tab, mirror, idx, midx, vw)
    assert np.array_equal(np.asarray(got),
                          np.asarray(pg.gather_rows(tab, idx, vw)))
    assert np.array_equal(
        np.asarray(got),
        np.asarray(pg._xla_hot_gather(tab, mirror, idx, midx, vw)))


def test_gather_rows_hot_duplicates_straddle_boundary(rng):
    """The adversarial batch: heavy duplication of the two rows on either
    side of hot_n — the exact lanes where a partition bug would read the
    wrong tier — interleaved so hot/cold alternate within the ring."""
    n, hot, vw = 100, 50, 3
    tab = jnp.asarray(rng.integers(0, 1 << 32, n * vw, np.int64)
                      .astype(np.uint32))
    mirror = tab[:hot * vw]
    idx = jnp.asarray(np.tile([hot - 1, hot, hot - 1, hot - 1, hot, hot],
                              32).astype(np.int32))
    midx = jnp.where(idx < hot, idx, -1)
    got = pg.gather_rows_hot(tab, mirror, idx, midx, vw)
    assert np.array_equal(np.asarray(got),
                          np.asarray(pg.gather_rows(tab, idx, vw)))


def test_scatter_rows_hot_matches_double_scatter(rng):
    n, hot, vw, k = 200, 37, 3, 300
    tab = jnp.asarray(rng.integers(0, 1 << 32, n * vw, np.int64)
                      .astype(np.uint32))
    mirror = tab[:hot * vw]
    # unique rows among masked lanes (the engines' one-writer contract),
    # straddling the boundary
    perm = rng.permutation(n)[: k % n if k % n else n]
    rows = np.zeros(k, np.int32)
    mask = np.zeros(k, bool)
    rows[: len(perm)] = perm
    mask[: len(perm)] = rng.random(len(perm)) < 0.6
    rows_j = jnp.asarray(rows)
    midx = jnp.where(rows_j < hot, rows_j, -1)
    mask_j = jnp.asarray(mask)
    vals = jnp.asarray(rng.integers(0, 1 << 32, k * vw, np.int64)
                       .astype(np.uint32))
    t_p, m_p = pg.scatter_rows_hot(jnp.array(tab), jnp.array(mirror),
                                   rows_j, midx, mask_j, vals, vw)
    t_x, m_x = pg.hot_scatter(jnp.array(tab), jnp.array(mirror), rows_j,
                              midx, mask_j, vals, vw, use_pallas=False)
    assert np.array_equal(np.asarray(t_p), np.asarray(t_x))
    assert np.array_equal(np.asarray(m_p), np.asarray(m_x))
    # write-through coherence: the mirror IS the table prefix afterwards
    assert np.array_equal(np.asarray(t_p)[: hot * vw], np.asarray(m_p))


@pytest.mark.parametrize("m,row_space,hot_n,seed", [
    (64, 8, 4, 0),     # brutal duplication, boundary inside the row set
    (64, 1000, 40, 1),  # mostly conflict-free, 4%-style prefix
    (10, 3, 1, 2),      # m > ring depth barely
    (130, 16, 8, 4),    # several ring wraps, half the rows hot
])
def test_lock_arbitrate_hot_prefix_bit_identical(m, row_space, hot_n,
                                                 seed):
    """The VMEM arb-prefix residency changes only DMA endpoints: grants
    and stamps must match both the hot_n=0 kernel and the XLA chain on
    adversarial duplicate/held batches straddling the prefix."""
    r = np.random.default_rng(seed)
    n1 = max(row_space + 1, 32)
    arb0 = np.zeros(n1, np.uint32)
    for row in r.choice(row_space, max(1, row_space // 3), replace=False):
        step = r.choice([3, 4])
        arb0[row] = np.uint32((step << td.K_ARB) | r.integers(0, 100))
    t = jnp.asarray(5, U32)
    rows = jnp.asarray(r.integers(0, row_space, m).astype(np.int32))
    act = jnp.asarray(r.random(m) < 0.75)
    a_0, g_0 = pg.lock_arbitrate(jnp.asarray(arb0), rows, act, t,
                                 td.K_ARB)
    a_h, g_h = pg.lock_arbitrate(jnp.asarray(arb0), rows, act, t,
                                 td.K_ARB, hot_n=hot_n)
    assert np.array_equal(np.asarray(a_0), np.asarray(a_h))
    assert np.array_equal(np.asarray(g_0), np.asarray(g_h))


# ------------------------------------------------- resolve + probe cache


def test_resolve_use_hotset_env(monkeypatch):
    monkeypatch.delenv("DINT_USE_HOTSET", raising=False)
    assert pg.resolve_use_hotset(None) is False
    monkeypatch.setenv("DINT_USE_HOTSET", "0")
    assert pg.resolve_use_hotset(None) is False
    monkeypatch.setenv("DINT_USE_HOTSET", "1")
    assert pg.resolve_use_hotset(None) is True
    assert pg.resolve_use_hotset(False) is False      # explicit wins


def test_probe_cache_is_per_kernel(monkeypatch):
    """The round-10 probe fix: a second kernels_available call that only
    changes the OTHER kernel's geometry must hit the gather probe's
    cache — proven by breaking gather_rows after the first call."""
    pg._probe_cache.clear()
    assert pg.kernels_available(n_idx=96, m_lock=24) is True

    def boom(*a, **k):
        raise RuntimeError("probe must not re-run (simulated)")

    monkeypatch.setattr(pg, "gather_rows", boom)
    # same gather geometry, no lock probe requested: pure cache hit
    assert pg.kernels_available(n_idx=96, m_lock=None) is True
    # same gather geometry, NEW lock geometry: only the lock re-probes
    assert pg.kernels_available(n_idx=96, m_lock=12) is True
    pg._probe_cache.clear()


def test_broken_hot_kernel_degrades_to_xla_partition(monkeypatch, caplog):
    """Mosaic rejection of the hot kernels costs the VMEM residency,
    never the partition: the builder serves the hot set via the XLA
    index-compare route and outputs stay correct."""
    pg._probe_cache.clear()

    def boom(*a, **k):
        raise RuntimeError("Mosaic lowering failed (simulated)")

    monkeypatch.setattr(pg, "gather_rows_hot", boom)
    with caplog.at_level("WARNING", logger="dint_tpu.pallas"):
        assert pg.hot_kernels_available(n_idx=64) is False
    assert any("falling back" in r.message for r in caplog.records)
    # bypass the builder memo: this build must see the broken kernel,
    # and the degraded build must not be cached for healthy callers
    sd.build_pipelined_runner.cache.clear()
    run_f, init, drain = sd.build_pipelined_runner(
        100, w=16, cohorts_per_block=2, use_pallas=True, use_hotset=True)
    carry = init(sd.create(100))
    carry, s = run_f(carry, jax.random.PRNGKey(0))
    db, tail = drain(carry)
    tot = (np.asarray(s, np.int64).sum(axis=0)
           + np.asarray(tail, np.int64).sum(axis=0))
    assert int(tot[sd.STAT_ATTEMPTED]) == 2 * 16
    assert db.hot_n > 0                       # the partition still ran
    pg._probe_cache.clear()
    sd.build_pipelined_runner.cache.clear()


# --------------------------------------------- end-to-end: smallbank


def _run_sb(use_hotset, use_pallas, n=300, blocks=3):
    db = sd.create(n)
    run_f, init, drain = sd.build_pipelined_runner(
        n, w=64, cohorts_per_block=2, use_pallas=use_pallas,
        use_hotset=use_hotset)
    carry = init(db)
    tot = np.zeros(sd.N_STATS, np.int64)
    for i in range(blocks):
        carry, s = run_f(carry, jax.random.fold_in(jax.random.PRNGKey(3),
                                                   i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    db, tail = drain(carry)
    return db, tot + np.asarray(tail, np.int64).sum(axis=0)


def _same_shared_state(db0, db1, leaves, log=True):
    for leaf in leaves:
        assert np.array_equal(np.asarray(getattr(db0, leaf)),
                              np.asarray(getattr(db1, leaf))), leaf
    if log:
        assert np.array_equal(np.asarray(db0.log.entries),
                              np.asarray(db1.log.entries))
        assert np.array_equal(np.asarray(db0.log.head),
                              np.asarray(db1.log.head))


def test_smallbank_dense_hotset_bit_identical(monkeypatch):
    """ISSUE 5 acceptance pin: DINT_USE_HOTSET=1 (env route, the exact
    production spelling, at the workload's hot_frac=0.04) reproduces the
    default path's stats, balances, stamps, and log rings bit for bit on
    BOTH serving routes, and the mirror coherence invariant holds."""
    db0, t0 = _run_sb(False, False)
    monkeypatch.setenv("DINT_USE_HOTSET", "1")
    db1, t1 = _run_sb(None, False)            # env route
    db2, t2 = _run_sb(None, True)             # + VMEM kernels
    assert t0.tolist() == t1.tolist() == t2.tolist()
    assert int(t0[sd.STAT_COMMITTED]) > 0
    for db in (db1, db2):
        _same_shared_state(db0, db, ("bal", "x_step", "s_step", "step"))
        hn, n = db.hot_n, db.n_accounts
        assert hn == max(1, int(n * wl.SB_HOT_FRAC))
        idx = np.concatenate([np.arange(hn), n + np.arange(hn)])
        assert np.array_equal(np.asarray(db.bal)[idx],
                              np.asarray(db.hot_bal))
        assert np.array_equal(np.asarray(db.x_step)[idx],
                              np.asarray(db.hot_x))
        assert np.array_equal(np.asarray(db.s_step)[idx],
                              np.asarray(db.hot_s))
    # conservation on the hot path
    start = 2 * 300 * 1000
    assert int(np.asarray(sd.total_balance(db2))) \
        == start + int(t2[sd.STAT_BAL_DELTA])


def test_smallbank_hashed_locks_skip_stamp_mirror(monkeypatch):
    """Above the slot cap the lock tables hash (cold accounts conflate
    onto hot slots), so the stamp mirror must NOT exist — only balances
    mirror — and outputs stay bit-identical."""
    monkeypatch.setattr(sd, "MAX_LOCK_SLOTS", 128)
    db0, t0 = _run_sb(False, False, n=200)
    db1, t1 = _run_sb(True, False, n=200)
    assert db1.hot_x is None and db1.hot_s is None
    assert db1.hot_bal is not None
    assert t0.tolist() == t1.tolist()
    _same_shared_state(db0, db1, ("bal", "x_step", "s_step", "step"))


# ----------------------------------------------- end-to-end: sharded


@pytest.mark.slow  # ~11s; the round-10 rule — dense + store hot pins stay tier-1
def test_dense_sharded_sb_hotset_bit_identical():
    """Two configs in tier-1 (baseline vs hot tier on the VMEM kernels —
    the XLA-partition route is pinned on single-chip above); one shard_map
    compile per config keeps the test inside the tier-1 budget."""
    from dint_tpu.parallel import dense_sharded_sb as dsb

    def run(uh, up):
        mesh = dsb.make_mesh(8)
        state = dsb.create_sharded_sb(mesh, 8, 400)
        run_f, init, drain = dsb.build_sharded_sb_runner(
            mesh, 8, 400, w=32, cohorts_per_block=2, use_pallas=up,
            use_hotset=uh)
        carry = init(state)
        tot = np.zeros(dsb.N_STATS, np.int64)
        for i in range(2):
            carry, s = run_f(carry,
                             jax.random.fold_in(jax.random.PRNGKey(2), i))
            tot += np.asarray(s, np.int64).sum(axis=0)
        state, tail = drain(carry)
        return state, tot + np.asarray(tail, np.int64).sum(axis=0)

    s0, t0 = run(False, False)
    s2, t2 = run(True, True)
    assert t0.tolist() == t2.tolist()
    assert int(t0[1]) > 0                      # committed
    for s in (s2,):
        _same_shared_state(s0, s, ("bal", "bck_bal", "x_step", "s_step",
                                   "step"))
        hl = s.hot_loc
        n_loc = np.asarray(s.bal).shape[1] // 2
        idx = np.concatenate([np.arange(hl), n_loc + np.arange(hl)])
        assert np.array_equal(np.asarray(s.bal)[:, idx],
                              np.asarray(s.hot_bal))
        assert np.array_equal(np.asarray(s.x_step)[:, idx],
                              np.asarray(s.hot_x))
        assert np.array_equal(np.asarray(s.s_step)[:, idx],
                              np.asarray(s.hot_s))


# ----------------------------------------------- end-to-end: skewed TATP


@pytest.mark.slow
def test_tatp_dense_hotset_bit_identical():
    """Skewed-TATP experiment route (builder kwarg; off by default):
    meta/magic gathers, write-through installs, and the VMEM arb-prefix
    lock pass — bit-identical stats, tables, stamps, logs. slow-marked:
    TATP's hot tier is the off-by-default experiment route, and its
    kernel mechanics (hot gather/scatter parity, the arb-prefix lock
    pass) are pinned by the tier-1 kernel tests above."""
    def run(uh, up):
        db = td.populate(np.random.default_rng(0), 200, val_words=4)
        run_f, init, drain = td.build_pipelined_runner(
            200, w=64, val_words=4, cohorts_per_block=2, use_pallas=up,
            use_hotset=uh, hot_frac=0.2)
        carry = init(db)
        tot = np.zeros(td.N_STATS, np.int64)
        for i in range(3):
            carry, s = run_f(carry,
                             jax.random.fold_in(jax.random.PRNGKey(0), i))
            tot += np.asarray(s, np.int64).sum(axis=0)
        db, tail = drain(carry)
        return db, tot + np.asarray(tail, np.int64).sum(axis=0)

    db0, t0 = run(False, False)
    db1, t1 = run(True, False)
    db2, t2 = run(True, True)
    assert t0.tolist() == t1.tolist() == t2.tolist()
    assert int(t0[td.STAT_COMMITTED]) > 0
    for db in (db1, db2):
        _same_shared_state(db0, db, ("val", "meta", "arb", "step"))
        hn = db.hot_n
        assert np.array_equal(np.asarray(db.meta)[:hn],
                              np.asarray(db.hot_meta))
        assert np.array_equal(np.asarray(db.val)[: hn * 4],
                              np.asarray(db.hot_val))


def test_tatp_dense_hotset_off_by_default(monkeypatch):
    """TATP is uniform: DINT_USE_HOTSET must NOT turn the TATP hot tier
    on — only the explicit builder kwarg does."""
    monkeypatch.setenv("DINT_USE_HOTSET", "1")
    run_f, init, _ = td.build_pipelined_runner(50, w=16, val_words=4,
                                               cohorts_per_block=2)
    carry = init(td.populate(np.random.default_rng(0), 50, val_words=4))
    assert carry[0].hot_n == 0 and carry[0].hot_meta is None


# --------------------------------------------- end-to-end: store engine


def test_store_hotset_bit_identical(rng):
    """The Zipfian store micro's engine: replies and table bit-identical
    with the hot tier threaded (both routes), mirror coherent with every
    currently-present hot key."""
    from dint_tpu.clients.micro import STORE_MAGIC, make_store_table
    from dint_tpu.engines import store
    from dint_tpu.engines.types import Op, make_batch
    from dint_tpu.ops import hashing
    from dint_tpu.tables import kv

    n_keys, width, vw, hot_n = 2000, 256, 10, 500

    def run(hot_on, up):
        r = np.random.default_rng(7)
        table = make_store_table(n_keys)
        hot = store.attach_hot(table, hot_n) if hot_on else None
        reps = []
        for _ in range(4):
            keys = wl.zipf_keys(r, width, int(n_keys * 1.2))
            u = r.random(width)
            ops = np.where(u < 0.5, Op.GET,
                           np.where(u < 0.8, Op.SET,
                                    np.where(u < 0.9, Op.INSERT,
                                             Op.DELETE))).astype(np.int32)
            vals = np.zeros((width, vw), np.uint32)
            vals[:, 0] = r.integers(0, 1 << 30, width)
            vals[:, 1] = STORE_MAGIC
            batch = make_batch(ops, keys, vals, width=width, val_words=vw)
            if hot is None:
                table, rep = store.step(table, batch)
            else:
                table, rep, hot = store.step(table, batch, hot=hot,
                                             use_pallas=up)
            reps.append(jax.tree.map(np.asarray, rep))
        return table, hot, reps

    t0, _, r0 = run(False, False)
    t1, h1, r1 = run(True, False)
    t2, h2, r2 = run(True, True)
    for other in (r1, r2):
        for a, b in zip(r0, other):
            for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                assert np.array_equal(la, lb)
    for t in (t1, t2):
        for leaf in ("key_hi", "key_lo", "val", "ver", "valid"):
            assert np.array_equal(np.asarray(getattr(t0, leaf)),
                                  np.asarray(getattr(t, leaf))), leaf
    # mirror == table for every hot key the probe can hit
    klo = jnp.arange(hot_n, dtype=U32)
    khi = jnp.zeros((hot_n,), U32)
    b1, b2 = hashing.bucket_pair(khi, klo, t1.n_buckets)
    hit, _, _, val, ver, _, _ = kv.probe(t1, khi, klo, b1, b2)
    hitn = np.asarray(hit)
    assert hitn.any()
    for h in (h1, h2):
        assert np.array_equal(np.asarray(val)[hitn],
                              np.asarray(h.val).reshape(hot_n, vw)[hitn])
        assert np.array_equal(np.asarray(ver)[hitn],
                              np.asarray(h.ver)[hitn])


@pytest.mark.slow
def test_store_cache_hotset_bit_identical():
    """Cache-mode store: replies, miss vector, MASKED flush/evicted
    records, and cache tables bit-identical across all three policies
    with the in-cache hot tier on (both routes). Flush/evicted values of
    mask-False lanes are don't-cares by contract (the host applies only
    masked lanes), so comparison is on the masked set. slow-marked (9
    jitted configs): the full-table store engine's hot tier — the same
    HotKV partition — is pinned in tier-1 above."""
    from dint_tpu.engines import store_cache as sc
    from dint_tpu.engines.types import Op, make_batch

    vw = 10

    def run(hot_keys, up, policy):
        cache = sc.create(64, val_words=vw, hot_keys=hot_keys)
        outs = []
        r = np.random.default_rng(3)
        for _ in range(4):
            keys = r.integers(1, 400, 128).astype(np.uint64)
            ops = np.where(r.random(128) < 0.6, Op.GET,
                           Op.SET).astype(np.int32)
            vals = np.zeros((128, vw), np.uint32)
            vals[:, 0] = r.integers(0, 99, 128)
            batch = make_batch(ops, keys, vals, width=128, val_words=vw)
            cache, rep, miss, flush = sc.cache_step(cache, batch,
                                                    policy=policy,
                                                    use_pallas=up)
            m = np.asarray(miss)
            rk = keys[m][:32]
            pad = 64
            rkl = np.zeros(pad, np.uint32)
            rkl[: len(rk)] = rk.astype(np.uint32)
            rv = np.zeros((pad, vw), np.uint32)
            rv[:, 0] = 7
            rver = np.zeros(pad, np.uint32)
            rver[: len(rk)] = 1
            mask = np.zeros(pad, bool)
            mask[: len(rk)] = True
            cache, ev = sc.refill(
                cache, jnp.zeros(pad, U32), jnp.asarray(rkl),
                jnp.asarray(rv), jnp.asarray(rver), jnp.zeros(pad, U32),
                jnp.zeros(pad, U32), jnp.asarray(mask))
            fm = np.asarray(flush["mask"])
            em = np.asarray(ev["mask"])
            outs.append((jax.tree.map(np.asarray, rep), m, fm,
                         np.asarray(flush["val"])[fm],
                         np.asarray(flush["ver"])[fm],
                         em, np.asarray(ev["val"])[em]))
        return cache, outs

    for pol in (sc.WB_BLOOM, sc.WB_NOBLOOM, sc.WT):
        c0, o0 = run(0, False, pol)
        c1, o1 = run(300, False, pol)
        c2, o2 = run(300, True, pol)
        for other in (o1, o2):
            for oa, ob in zip(o0, other):
                for la, lb in zip(jax.tree.leaves(oa),
                                  jax.tree.leaves(ob)):
                    assert np.array_equal(la, lb), pol
        for c in (c1, c2):
            for leaf in ("key_hi", "key_lo", "val", "ver", "valid"):
                assert np.array_equal(np.asarray(getattr(c0.kv, leaf)),
                                      np.asarray(getattr(c.kv, leaf))), \
                    (pol, leaf)
            assert np.array_equal(np.asarray(c0.dirty),
                                  np.asarray(c.dirty)), pol


# ------------------------------------------------------------ workload


def test_zipf_keys_hot_head():
    """rank == key id: the Zipfian head concentrates on the smallest ids
    (the dintcache prefix), in range, strongly skewed at theta=0.99."""
    rng = np.random.default_rng(0)
    k = wl.zipf_keys(rng, 100_000, 10_000)
    assert k.min() >= 1 and k.max() <= 10_000
    assert (k <= 400).mean() > 0.5            # 4% of keys, >50% of draws
    # theta=0 degenerates toward uniform
    u = wl.zipf_keys(rng, 100_000, 10_000, theta=0.0)
    assert abs((u <= 400).mean() - 0.04) < 0.01


def test_store_client_zipf_hotset_waves():
    """The micro client end-to-end: Zipfian + hot tier threaded through
    the jitted step, magic intact, goodput == batch width."""
    from dint_tpu.clients import micro

    rng = np.random.default_rng(0)
    c = micro.StoreClient.populated(2000, width=256, key_dist="zipfian",
                                    use_hotset=True, hot_frac=0.1)
    assert c.use_hotset
    for _ in range(3):
        assert c.run_wave(rng) == 256
