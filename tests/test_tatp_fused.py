"""Fused flat-state TATP engine: invariants + parity with the stacked
pipeline (same populate data, same accounting contract)."""
import jax
import numpy as np
import pytest

from dint_tpu.clients import tatp_client as tc
from dint_tpu.engines import tatp_fused as tf, tatp_pipeline as tp

N_SUB = 64
VW = 4
CFB = 1 << 8
CFL = 1 << 8


def _state():
    rng = np.random.default_rng(7)
    shards, _ = tc.populate_shards(rng, N_SUB, val_words=VW,
                                   cf_buckets=1 << 10, cf_lock_slots=1 << 10)
    return tf.from_replicas(shards, N_SUB, cf_buckets=CFB, cf_lock_slots=CFL,
                            cf_slots=8, log_lanes=4,
                            log_capacity=1 << 10), shards


def _run(state, w=128, blocks=3, per=3, validate=True):
    run = tf.build_runner(N_SUB, w=w, cf_buckets=CFB, cf_lock_slots=CFL,
                          log_lanes=4, cohorts_per_block=per,
                          validate=validate)
    key = jax.random.PRNGKey(0)
    total = np.zeros(tf.N_STATS, np.int64)
    for i in range(blocks):
        state, stats = run(state, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    return state, total


def test_accounting_and_magic():
    state, _ = _state()
    state, total = _run(state)
    attempted = total[tf.STAT_ATTEMPTED]
    assert attempted == 3 * 3 * 128
    assert (total[tf.STAT_COMMITTED] + total[tf.STAT_AB_LOCK]
            + total[tf.STAT_AB_MISSING] + total[tf.STAT_AB_VALIDATE]
            == attempted)
    assert total[tf.STAT_MAGIC_BAD] == 0
    assert total[tf.STAT_OVERFLOW] == 0
    assert total[tf.STAT_COMMITTED] > 0.5 * attempted


def test_replicas_stay_identical():
    """Replication contract: after full cohorts the 3 replicas' bank rows
    (values + versions) and cf contents are bit-identical; no lock leaks."""
    state, _ = _state()
    state, _ = _run(state, blocks=4)
    vw = state.val_words
    p1, nr, _ = tf._layout(N_SUB)
    bank = np.asarray(state.bank)[: tf.S * nr]
    b = bank.reshape(tf.S, nr, vw + 2)
    # values + versions identical across replicas
    np.testing.assert_array_equal(b[0, :, :vw + 1], b[1, :, :vw + 1])
    np.testing.assert_array_equal(b[0, :, :vw + 1], b[2, :, :vw + 1])
    # no lock bit left set
    assert (b[:, :, vw + 1] == 0).all()
    assert (np.asarray(state.cf_lock) == 0).all()
    # cf: same multiset of (key, ver, val) per replica
    cf = np.asarray(state.cf).reshape(tf.S, -1, 2 + vw)
    def live(rep):
        rows = rep[rep[:, 1] > 0]
        return sorted(map(tuple, rows))
    assert live(cf[0]) == live(cf[1]) == live(cf[2])


def test_log_heads_advance_uniformly():
    state, _ = _state()
    h0 = np.asarray(state.log_head).reshape(tf.S, -1).sum(axis=1)
    state, total = _run(state, blocks=2)
    h1 = np.asarray(state.log_head).reshape(tf.S, -1).sum(axis=1)
    adv = h1 - h0
    # every replica logs every committed write record
    assert adv[0] == adv[1] == adv[2]
    assert adv[0] > 0


def test_abort_rate_matches_stacked_pipeline():
    """Same workload params -> fused flat engine and stacked pipeline agree
    on abort rate within noise (both certify per-cohort)."""
    state, shards = _state()
    state, total = _run(state, w=256, blocks=2, per=4)
    fused_rate = 1 - total[tf.STAT_COMMITTED] / total[tf.STAT_ATTEMPTED]

    run = tp.build_runner(N_SUB, w=256, val_words=VW, cohorts_per_block=8)
    _, stats = run(tp.stack_shards([jax.tree.map(jax.numpy.array, s)
                                    for s in shards]), jax.random.PRNGKey(5))
    tot = np.asarray(stats, np.int64).sum(axis=0)
    stacked_rate = 1 - tot[tp.STAT_COMMITTED] / tot[tp.STAT_ATTEMPTED]
    assert abs(fused_rate - stacked_rate) < 0.08
