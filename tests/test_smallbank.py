import jax
import numpy as np

from dint_tpu.clients import smallbank_client as sbc
from dint_tpu.clients import workloads as wl
from dint_tpu.engines import smallbank
from dint_tpu.engines.types import Op, Reply, make_batch

VW = 2


def _batch(ops, tbls, accts, vals=None, vers=None, width=64):
    return make_batch(ops, np.asarray(accts, np.uint64), vals, vers=vers,
                      tables=np.asarray(tbls, np.int32), width=width, val_words=VW)


def test_fused_lock_read_and_commit():
    shard = smallbank.create(100, val_words=VW, log_capacity=1 << 12)
    vals = np.zeros((100, VW), np.uint32)
    vals[:, 0] = 50
    vals[:, 1] = wl.SB_MAGIC
    shard = shard.replace(
        sav=shard.sav.replace(val=jax.numpy.asarray(vals.reshape(-1)),
                              ver=jax.numpy.ones(100, jax.numpy.uint32)),
        chk=shard.chk.replace(val=jax.numpy.asarray(vals.reshape(-1)),
                              ver=jax.numpy.ones(100, jax.numpy.uint32)))
    step = jax.jit(smallbank.step)

    # X-lock + fused read; conflicting second X rejected; S on other table ok
    b = _batch([Op.ACQ_X_READ, Op.ACQ_X_READ, Op.ACQ_S_READ],
               [smallbank.CHECKING, smallbank.CHECKING, smallbank.SAVINGS],
               [7, 7, 7])
    shard, rep = step(shard, b)
    rt = np.asarray(rep.rtype)
    assert list(rt[:3]) == [Reply.GRANT, Reply.REJECT, Reply.GRANT]
    assert np.asarray(rep.val)[0, 0] == 50
    assert np.asarray(rep.val)[0, 1] == wl.SB_MAGIC
    assert np.asarray(rep.ver)[0] == 1

    # commit new value on checking(7), then release; next reader sees it
    nv = np.zeros((1, VW), np.uint32)
    nv[0, 0] = 123
    nv[0, 1] = wl.SB_MAGIC
    b = _batch([Op.COMMIT_PRIM], [smallbank.CHECKING], [7], nv,
               vers=np.array([2], np.uint32))
    shard, rep = step(shard, b)
    assert np.asarray(rep.rtype)[0] == Reply.ACK
    b = _batch([Op.REL_X], [smallbank.CHECKING], [7])
    shard, rep = step(shard, b)
    b = _batch([Op.ACQ_S_READ], [smallbank.CHECKING], [7])
    shard, rep = step(shard, b)
    assert np.asarray(rep.rtype)[0] == Reply.GRANT
    assert np.asarray(rep.val)[0, 0] == 123
    assert np.asarray(rep.ver)[0] == 2


def test_commit_then_acquire_same_batch():
    # commit installs before acquires read (batch serialization contract)
    shard = smallbank.create(10, val_words=VW, log_capacity=1 << 12)
    nv = np.zeros((2, VW), np.uint32)
    nv[0, 0] = 9
    b = _batch([Op.COMMIT_PRIM, Op.ACQ_S_READ],
               [smallbank.SAVINGS, smallbank.SAVINGS], [3, 3], nv,
               vers=np.array([5, 0], np.uint32))
    shard, rep = step_once(shard, b)
    assert np.asarray(rep.rtype)[1] == Reply.GRANT
    assert np.asarray(rep.val)[1, 0] == 9
    assert np.asarray(rep.ver)[1] == 5


def step_once(shard, b):
    return jax.jit(smallbank.step)(shard, b)


def test_end_to_end_pipeline_and_invariants(rng):
    n_accounts = 512
    shards = sbc.init_shards(n_accounts, init_balance=1000)
    coord = sbc.Coordinator(shards, width=1024)
    base_total = sbc.total_balance(coord.shards)

    # conserving mix only: amalgamate / balance / send_payment
    mix = np.array([0.3, 0.2, 0.0, 0.5, 0.0, 0.0])
    for _ in range(4):
        ttype, a1, a2 = wl.sb_make_txns(rng, 256, n_accounts, mix=mix)
        coord.run_cohort(ttype, a1, a2)

    st = coord.stats
    assert st.attempted == 4 * 256
    assert st.committed > 0
    assert st.committed + st.aborted_lock + st.aborted_logic >= st.attempted * 0.99

    # invariant 1: money conserved (conserving mix)
    assert sbc.total_balance(coord.shards) == base_total

    # invariant 2: all locks released at the end
    for s in coord.shards:
        assert int(np.asarray(s.sav_sh).sum()) == 0
        assert int(np.asarray(s.sav_ex).sum()) == 0
        assert int(np.asarray(s.chk_sh).sum()) == 0
        assert int(np.asarray(s.chk_ex).sum()) == 0

    # invariant 3: replicas converged (every commit reached all 3)
    for tbl in ("sav", "chk"):
        v0 = np.asarray(getattr(coord.shards[0], tbl).val)
        r0 = np.asarray(getattr(coord.shards[0], tbl).ver)
        for s in coord.shards[1:]:
            assert np.array_equal(v0, np.asarray(getattr(s, tbl).val))
            assert np.array_equal(r0, np.asarray(getattr(s, tbl).ver))

    # invariant 4: log got one entry per written key per shard
    heads = [int(np.asarray(s.log.head).sum()) for s in coord.shards]
    assert heads[0] == heads[1] == heads[2]
    assert heads[0] > 0


def test_full_mix_runs(rng):
    n_accounts = 256
    shards = sbc.init_shards(n_accounts)
    coord = sbc.Coordinator(shards, width=1024)
    for _ in range(3):
        ttype, a1, a2 = wl.sb_make_txns(rng, 200, n_accounts)
        coord.run_cohort(ttype, a1, a2)
    assert coord.stats.committed > 0
    # versions monotone: ver >= 1 everywhere, and bounded by 1 + commits
    for s in coord.shards:
        assert (np.asarray(s.sav.ver) >= 1).all()
