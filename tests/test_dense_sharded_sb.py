"""Multi-chip dense SmallBank: TRUE cross-device transactions over the
mesh — a SendPayment's two accounts land on different devices, its locks
are granted remotely, and global balance conservation must still hold."""
import jax
import numpy as np

from dint_tpu.engines import smallbank_dense as sd
from dint_tpu.parallel import dense_sharded_sb as dsb

D = 8


def _run(n_accounts, w, blocks, seed=0, **kw):
    mesh = dsb.make_mesh(D)
    state = dsb.create_sharded_sb(mesh, D, n_accounts)
    base = dsb.total_balance_global(state)
    run, init, drain = dsb.build_sharded_sb_runner(
        mesh, D, n_accounts, w=w, cohorts_per_block=2, **kw)
    carry = init(state)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(dsb.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    state, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return state, total, base


def test_accounting_closes_and_balance_conserved_globally():
    state, total, base = _run(n_accounts=4096, w=128, blocks=3)
    attempted = int(total[dsb.STAT_ATTEMPTED])
    committed = int(total[dsb.STAT_COMMITTED])
    assert attempted == 3 * 2 * 128 * D     # every device contributes w
    assert committed > 0
    assert committed + int(total[dsb.STAT_AB_LOCK]) \
        + int(total[dsb.STAT_AB_LOGIC]) == attempted
    # routing slack holds: no destination bucket overflowed at this width
    assert int(total[dsb.STAT_OVERFLOW]) == 0
    final = dsb.total_balance_global(state)
    want = int(total[dsb.STAT_BAL_DELTA])
    assert (final - base) % (1 << 32) == want % (1 << 32)


def test_cross_device_transactions_commit():
    """SendPayment-only mix: every txn X-locks TWO accounts; with 8-way
    round-robin partitioning a1 and a2 usually live on different devices,
    so a nonzero commit count proves remote lock grants + remote installs
    work end to end (and conservation pins their correctness)."""
    mix = np.zeros(6)
    mix[3] = 1.0          # SB_SEND_PAYMENT (wl.SB_MIX order)
    state, total, base = _run(n_accounts=1 << 14, w=64, blocks=3,
                              mix=mix, hot_prob=0.0)
    committed = int(total[dsb.STAT_COMMITTED])
    assert committed > 0
    final = dsb.total_balance_global(state)
    assert (final - base) % (1 << 32) == int(
        total[dsb.STAT_BAL_DELTA]) % (1 << 32)
    # SendPayment moves money between accounts: committed txns with zero
    # global delta is exactly conservation
    assert int(total[dsb.STAT_BAL_DELTA]) == 0


def test_backups_mirror_primaries():
    state, total, _ = _run(n_accounts=2048, w=64, blocks=4)
    bal = np.asarray(state.bal)          # [D, m1]
    bck = np.asarray(state.bck_bal)      # [D, 2*m1]
    m1 = bal.shape[1]
    for dev in range(D):
        for off, slot in ((1, 0), (2, 1)):
            holder = (dev + off) % D
            got = bck[holder, slot * m1:(slot + 1) * m1]
            assert np.array_equal(got[:-1], bal[dev, :-1]), (dev, off)


def test_hot_contention_rejects_across_devices():
    """Whole-keyspace hot set at w=1 per device: every cohort hits the
    same few accounts from 8 different devices; cross-device no-wait
    rejects must fire."""
    _, total, _ = _run(n_accounts=16, w=4, blocks=4, seed=2,
                       hot_frac=1.0, hot_prob=1.0)
    assert int(total[dsb.STAT_AB_LOCK]) > 0


def test_lost_device_balance_range_recovers_from_any_ring():
    """A lost device's primary balances rebuild from ANY of the 3 rings
    carrying its stream (entries log GLOBAL account ids; owner =
    acct % D separates streams)."""
    from dint_tpu import recovery

    n_accounts = 2048
    state, total, _ = _run(n_accounts=n_accounts, w=64, blocks=3)
    bal = np.asarray(state.bal)                  # [D, m1]
    entries = np.asarray(state.log.entries)      # [D, L*CAP, EW]
    heads = np.asarray(state.log.head)           # [D, L]
    lanes = state.log.lanes
    cap = entries.shape[1] // lanes

    for dead in (1, 5):
        for holder in (dead, (dead + 1) % D, (dead + 2) % D):
            rec = recovery.recover_sb_shard(
                n_accounts, dead, D,
                entries[holder].reshape(lanes, cap, -1), heads[holder],
                ring_owner=holder)
            assert np.array_equal(rec, bal[dead]), (dead, holder)

    # geometry check: the key_hi source tags expose a ring replayed under
    # the wrong n_shards (here: wrong ring_owner stands in for geometry
    # drift — tags no longer match acct % D)
    import pytest

    wrong = (1 + 3) % D
    with pytest.raises(ValueError, match="source tags"):
        recovery.recover_sb_shard(
            n_accounts, 1, D, entries[1].reshape(lanes, cap, -1),
            heads[1], ring_owner=wrong)


def test_route_overflow_fires_and_reconciles_with_monitor():
    """Adversarial routing: every txn hits ONE hot account, so every
    device aims all w*L lanes at a single destination bucket of capacity
    2*ceil(w*L/D) — overflow MUST fire. Overflowed lanes degrade to lock
    rejects (accounting still closes) and the psummed STAT_OVERFLOW
    total reconciles EXACTLY with dintmon's route_overflow counter."""
    from dint_tpu.monitor import counters as mon

    mesh = dsb.make_mesh(D)
    state = dsb.create_sharded_sb(mesh, D, 4096)
    base = dsb.total_balance_global(state)
    run, init, drain = dsb.build_sharded_sb_runner(
        mesh, D, 4096, w=64, cohorts_per_block=2,
        hot_frac=1.0 / 4096, hot_prob=1.0, monitor=True)
    carry = init(state)
    key = jax.random.PRNGKey(3)
    total = np.zeros(dsb.N_STATS, np.int64)
    for i in range(3):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    state, tail, cnt = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)

    overflow = int(total[dsb.STAT_OVERFLOW])
    assert overflow > 0
    # dropped lanes surface as lock aborts, never as lost txns
    attempted = int(total[dsb.STAT_ATTEMPTED])
    assert attempted == 3 * 2 * 64 * D
    assert int(total[dsb.STAT_COMMITTED]) + int(total[dsb.STAT_AB_LOCK]) \
        + int(total[dsb.STAT_AB_LOGIC]) == attempted
    # and conservation survives the drops
    final = dsb.total_balance_global(state)
    assert (final - base) % (1 << 32) == \
        int(total[dsb.STAT_BAL_DELTA]) % (1 << 32)
    # exact reconciliation: the stats plane and the counter plane count
    # the same event at the same site (source device, cohort completion)
    snap = mon.snapshot(cnt)
    assert snap["route_overflow"] == overflow
    assert snap["txn_attempted"] == attempted
